"""Phase-level timing of the serving-cache warm load (fresh process).

Usage: python experiments/warm_load_profile.py INDEX_DIR
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def main(index_dir: str) -> None:
    t0 = time.perf_counter()

    def mark(label):
        nonlocal t0
        t = time.perf_counter()
        print(f"{label:28s} {t - t0:8.2f}s", flush=True)
        t0 = t

    import jax

    print("devices:", jax.devices(), flush=True)
    mark("jax init")

    from tpu_ir.collection import DocnoMapping, Vocab
    from tpu_ir.index import format as fmt
    from tpu_ir.search.layout import load_serving_cache
    from tpu_ir.search.scorer import Scorer

    mark("imports")

    meta = fmt.IndexMetadata.load(index_dir)
    mark("metadata")
    vocab = Vocab.load(os.path.join(index_dir, fmt.VOCAB))
    mark("vocab")
    mapping = DocnoMapping.load(os.path.join(index_dir, fmt.DOCNOS))
    mark("docnos")
    doc_len = np.load(os.path.join(index_dir, fmt.DOCLEN))
    mark("doclen")

    cached = load_serving_cache(index_dir, meta=meta)
    assert cached is not None, "no cache hit!"
    tiers, df, norms = cached
    mark("cache key + mmap")

    s = Scorer(vocab=vocab, mapping=mapping, df=np.asarray(df),
               doc_len=doc_len, meta=meta, layout="sparse",
               index_dir=index_dir, tiers=tiers,
               doc_norms=np.asarray(norms))
    mark("Scorer.__init__ (dispatch)")
    import bench

    jax.block_until_ready(bench.serving_arrays(s))
    mark("device uploads complete")

    # end-to-end sanity: Scorer.load in-process (second call re-CRCs)
    t0 = time.perf_counter()
    s2 = Scorer.load(index_dir, layout="sparse")
    jax.block_until_ready([s2.hot_tfs, s2.tier_docs])
    mark("full Scorer.load (again)")


if __name__ == "__main__":
    main(sys.argv[1])
