"""Differential build fuzz: random corpora through every build path.

For each seed: generate a corpus (random sizes, duplicate tokens,
unicode docs, empty docs, multiple files, optional gzip member), then

  1. build it four ways — in-memory, streaming, SPMD(4), streaming+SPMD —
     and require BYTE-IDENTICAL artifacts across all four;
  2. positions+store ride along on a subset of seeds (byte-compared too);
  3. split the corpus in half, build each, merge — byte-identical to the
     one-shot build (the merge determinism contract);
  4. query the built index in --compat mode and require EXACT agreement
     with the pure-Python CompatIndex oracle on scores and order.

Usage: python experiments/fuzz_builds.py [N_SEEDS] [FIRST_SEED]
Runs hermetically on the CPU backend with an 8-virtual-device mesh.
"""

import gzip
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")
import jax._src.xla_bridge as xb

for _n in list(xb._backend_factories):
    if _n != "cpu":
        xb._backend_factories.pop(_n, None)

import numpy as np

WORDS = ["salmon", "fish", "river", "bear", "honey", "fox", "dog", "run",
         "the", "a", "of", "quick", "lazy", "gold", "market", "naïve",
         "café", "x", "zz", "investor", "asset", "jump", "season"]


def make_corpus(rng, tmp):
    """1-3 files, 1-40 docs, some empty/unicode/dup-heavy; maybe gzip."""
    n_docs = int(rng.integers(1, 41))
    docids = [f"D-{rng.integers(0, 10**6):06d}-{i}" for i in range(n_docs)]
    paths, recs = [], []
    for i, d in enumerate(docids):
        style = rng.integers(0, 10)
        if style == 0:
            body = ""                                   # empty doc
        elif style == 1:
            w = rng.choice(WORDS)
            body = " ".join([w] * int(rng.integers(1, 30)))  # dup-heavy
        else:
            body = " ".join(rng.choice(WORDS, int(rng.integers(1, 60))))
        recs.append(f"<DOC>\n<DOCNO> {d} </DOCNO>\n<TEXT>\n{body}\n"
                    f"</TEXT>\n</DOC>\n")
    n_files = int(rng.integers(1, 4))
    cuts = sorted(rng.choice(len(recs) + 1, n_files - 1)) if n_files > 1 \
        else []
    chunks = np.split(np.array(recs, dtype=object),
                      cuts) if recs else [np.array([], dtype=object)]
    for fi, chunk in enumerate(chunks):
        text = "".join(chunk)
        if rng.integers(0, 4) == 0:                    # ~25% gzip members
            p = os.path.join(tmp, f"c{fi}.trec.gz")
            with gzip.open(p, "wt") as f:
                f.write(text)
        else:
            p = os.path.join(tmp, f"c{fi}.trec")
            with open(p, "w") as f:
                f.write(text)
        paths.append(p)
    return paths, {d: r for d, r in zip(docids, recs)}


def artifact_bytes(idx):
    """name -> bytes for every non-job artifact. Serving caches are
    excluded; so is the docstore pair — its ARRIVAL order is
    path-dependent by design (the native scanner's fallback channel
    appends non-ASCII records after each chunk's native docs, perm
    resolves docno -> row), so stores are compared semantically."""
    out = {}
    for name in sorted(os.listdir(idx)):
        p = os.path.join(idx, name)
        if (os.path.isfile(p) and not name.startswith("serving-")
                and not name.startswith("docstore")):
            out[name] = open(p, "rb").read()
    return out


def require_identical(a_dir, b_dir, label):
    a, b = artifact_bytes(a_dir), artifact_bytes(b_dir)
    assert set(a) == set(b), (label, sorted(set(a) ^ set(b)))
    for name in a:
        assert a[name] == b[name], (label, name)
    from tpu_ir.index import docstore as ds

    if ds.available(a_dir) or ds.available(b_dir):
        assert ds.available(a_dir) and ds.available(b_dir), label
        sa, sb = ds.DocStore(a_dir), ds.DocStore(b_dir)
        n = len(sa._lengths)
        assert n == len(sb._lengths), label
        for dn in range(1, n + 1):
            assert sa.get_bytes(dn) == sb.get_bytes(dn), (label, dn)
        sa.close()
        sb.close()


def one_seed(seed: int) -> None:
    from tpu_ir.compat import CompatIndex
    from tpu_ir.index import build_index
    from tpu_ir.index.merge import merge_indexes
    from tpu_ir.index.streaming import build_index_streaming
    from tpu_ir.index.verify import verify_index
    from tpu_ir.search import Scorer

    rng = np.random.default_rng(seed)
    tmp = tempfile.mkdtemp(prefix=f"fuzz{seed}-")
    try:
        paths, docs = make_corpus(rng, tmp)
        k = 1 if rng.integers(0, 4) else 2
        positions = bool(rng.integers(0, 2)) and k == 1
        store = bool(rng.integers(0, 2))
        shards = int(rng.integers(1, 6))
        batch = int(rng.integers(1, 8))
        common = dict(k=k, num_shards=shards, compute_chargrams=False,
                      positions=positions)

        mem = os.path.join(tmp, "mem")
        build_index(paths, mem, chargram_ks=[], **common)
        if store:
            from tpu_ir.index.docstore import build_docstore

            build_docstore(paths, mem)
        stream = os.path.join(tmp, "stream")
        build_index_streaming(paths, stream, batch_docs=batch,
                              chargram_ks=[], store=store, **common)
        require_identical(mem, stream, f"seed{seed}:mem-vs-stream")

        # SPMD builds pin shard count = device count (4): compare against
        # a 4-shard in-memory build (mem itself when shards == 4)
        # (store coverage lives in the mem-vs-stream pair above; the SPMD
        # in-memory path has no store writer, so these run storeless)
        common4 = dict(common, num_shards=4)
        mem4 = os.path.join(tmp, "mem4")
        build_index(paths, mem4, chargram_ks=[], **common4)
        spmd = os.path.join(tmp, "spmd")
        build_index(paths, spmd, chargram_ks=[], spmd_devices=4, **common4)
        require_identical(mem4, spmd, f"seed{seed}:mem-vs-spmd")

        sstream = os.path.join(tmp, "sstream")
        build_index_streaming(paths, sstream, batch_docs=batch,
                              chargram_ks=[], spmd_devices=4, **common4)
        require_identical(mem4, sstream, f"seed{seed}:mem-vs-sstream")

        assert verify_index(mem)["ok"], f"seed{seed}: verify"

        # merge split-halves == one-shot (docids disjoint by construction;
        # skip when a half got only empty files — a valid corpus for the
        # main builds above, but not a buildable merge source)
        def half_has_docs(ps):
            from tpu_ir.collection import read_trec_corpus

            return any(True for _ in read_trec_corpus(ps))

        if (len(docs) >= 2 and len(paths) >= 2
                and half_has_docs(paths[:1]) and half_has_docs(paths[1:])):
            ia, ib = os.path.join(tmp, "ia"), os.path.join(tmp, "ib")
            build_index(paths[:1], ia, chargram_ks=[], **common)
            build_index(paths[1:], ib, chargram_ks=[], **common)
            if store:
                from tpu_ir.index.docstore import build_docstore

                build_docstore(paths[:1], ia)
                build_docstore(paths[1:], ib)
            merged = os.path.join(tmp, "merged")
            merge_indexes([ia, ib], merged, num_shards=shards,
                          compute_chargrams=False)
            require_identical(mem, merged, f"seed{seed}:mem-vs-merged")

        # compat-mode queries vs the pure-Python oracle: the engine drops
        # zero-score docs and sorts by exact score; the oracle keeps them
        # under the ceil comparator — compare the positive-score doc SETS
        # and per-doc scores (the established test_compat semantics)
        if k == 1:
            oracle = CompatIndex({d: r for d, r in docs.items()}, k=1)
            s = Scorer.load(mem, compat_int_idf=True)
            for _ in range(4):
                q = " ".join(rng.choice(WORDS, int(rng.integers(1, 3))))
                want = oracle.rank(q)
                if want is None:
                    continue
                got = dict(s.search(q, k=len(docs) + 1))
                want_pos = {d: ws for d, ws in want if ws > 0}
                if len(want) < 10:
                    # untruncated: positive-score doc sets must agree
                    assert set(got) == set(want_pos), (seed, q)
                for d, ws in want_pos.items():
                    # every oracle doc must appear with the exact score
                    # (the oracle's own top-10 cut may differ from the
                    # engine's at ceil-comparator near-ties, so only
                    # subset+score is assertable when truncated)
                    assert d in got, (seed, q, d)
                    assert abs(got[d] - ws) < 1e-4 * max(1.0, abs(ws)), (
                        seed, q, d)

        # serving-layout agreement: dense vs tiered-sparse vs sharded
        # must retrieve the same docs with ~equal scores for TF-IDF,
        # BM25 and the two-stage rerank, on random queries
        dense = Scorer.load(mem, layout="dense")
        sparse = Scorer.load(mem, layout="sparse")
        sharded = Scorer.load(mem4, layout="sharded")
        queries = [" ".join(rng.choice(WORDS, int(rng.integers(1, 4))))
                   for _ in range(3)]
        for scoring in ("tfidf", "bm25"):
            r_d = dense.search_batch(queries, scoring=scoring)
            r_p = sparse.search_batch(queries, scoring=scoring)
            r_s = sharded.search_batch(queries, scoring=scoring)
            for q, gd, gp, gs in zip(queries, r_d, r_p, r_s):
                for other, name in ((gp, "sparse"), (gs, "sharded")):
                    # rank-by-rank scores must agree...
                    assert len(gd) == len(other), (seed, scoring, name, q)
                    for (_, s1), (_, s2) in zip(gd, other):
                        assert abs(s1 - s2) < 1e-3 * max(1.0, abs(s1)), (
                            seed, scoring, name, q)
                    # ...but doc sets only ABOVE the k-th score's tie
                    # band: when several docs tie exactly at the cut, a
                    # last-ulp accumulation difference between the dense
                    # einsum and the tiered scatter legitimately flips
                    # which of them fills the final slots (seed 492)
                    floor = gd[-1][1] + 1e-3 * max(1.0, abs(gd[-1][1])) \
                        if gd else 0.0
                    top_d = {d for d, s in gd if s > floor}
                    top_o = {d for d, s in other if s > floor}
                    assert top_d == top_o, (seed, scoring, name, q)
        rr_d = dense.search_batch(queries, rerank=4)
        rr_p = sparse.search_batch(queries, rerank=4)
        rr_s = sharded.search_batch(queries, rerank=4)
        # stage-1 boundary check: when the 4th and 5th BM25 scores are an
        # fp near-tie, the layouts may legitimately pick different
        # candidate sets (dense einsum and tiered scatter sum the same
        # postings in different orders — seed 279 found a one-ulp tie),
        # so the strict rerank doc-set assert only applies to queries
        # with an unambiguous candidate cut
        b5 = dense.search_batch(queries, scoring="bm25", k=5)
        for q, gd, gp, gs, cand in zip(queries, rr_d, rr_p, rr_s, b5):
            if len(cand) >= 5 and cand[3][1] - cand[4][1] < 1e-4 * max(
                    1.0, abs(cand[3][1])):
                continue
            for other, name in ((gp, "sparse"), (gs, "sharded")):
                assert {d for d, _ in gd} == {d for d, _ in other}, (
                    seed, "rerank", name, q)

        # wildcard + fuzzy expansion vs fnmatch / Levenshtein oracles
        # (chargram builds on a subset of seeds; k=1 so the index vocab
        # IS the token vocab)
        if k == 1 and rng.integers(0, 3) == 0:
            import fnmatch as fn

            from tpu_ir.collection import Vocab
            from tpu_ir.index import format as fmt
            from tpu_ir.index.builder import build_chargram_artifacts
            from tpu_ir.search.wildcard import WildcardLookup

            vocab_terms = Vocab.load(os.path.join(mem, fmt.VOCAB)).terms
            build_chargram_artifacts(mem, vocab_terms, [2, 3])
            lookup = WildcardLookup.load(mem, 3)
            for _ in range(4):
                w = str(rng.choice(WORDS))
                cut = int(rng.integers(1, max(len(w), 2)))
                pat = w[:cut] + "*"
                if len(pat.replace("*", "")) < 2:
                    continue  # needs one full gram; lookup rejects
                want = sorted(t for t in vocab_terms
                              if fn.fnmatchcase(t, pat))
                got = sorted(lookup.expand(pat))
                assert got == want, (seed, pat, got, want)

            def lev(a, b):
                dp = list(range(len(b) + 1))
                for i, ca in enumerate(a, 1):
                    prev, dp[0] = dp[0], i
                    for j, cb in enumerate(b, 1):
                        prev, dp[j] = dp[j], min(
                            dp[j] + 1, dp[j - 1] + 1,
                            prev + (ca != cb))
                return dp[-1]

            for _ in range(2):
                w = str(rng.choice(WORDS))
                if len(w) < 3:
                    continue
                want = sorted(t for t in vocab_terms if lev(w, t) <= 1)
                got = sorted(t for t, d in lookup.fuzzy(w, max_edits=1))
                assert got == want, (seed, w, got, want)

        # phrase matching vs a brute-force text oracle (positions builds)
        if positions and k == 1:
            from tpu_ir.analysis import Analyzer

            an = Analyzer()
            toks_by_doc = {d: an.analyze(r) for d, r in docs.items()}
            sp = Scorer.load(mem)
            for _ in range(3):
                w1, w2 = rng.choice(WORDS, 2)
                p1, p2 = an.analyze(w1), an.analyze(w2)
                if len(p1) != 1 or len(p2) != 1:
                    continue
                t1, t2 = p1[0], p2[0]
                want_docs = {d for d, toks in toks_by_doc.items()
                             if any(a == t1 and b == t2
                                    for a, b in zip(toks, toks[1:]))}
                got = sp.search(f'"{w1} {w2}"', k=len(docs) + 1)
                assert {d for d, _ in got} == want_docs, (
                    seed, w1, w2, "phrase")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
        # every seed has fresh random shapes: without this the process
        # accumulates hundreds of compiled executables and dies with an
        # LLVM OOM around seed ~60
        jax.clear_caches()


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 25
    first = int(sys.argv[2]) if len(sys.argv) > 2 else 0
    for seed in range(first, first + n):
        one_seed(seed)
        print(f"seed {seed} ok", flush=True)
    print(f"ALL OK: {n} seeds from {first}")


if __name__ == "__main__":
    main()
