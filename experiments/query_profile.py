"""Where does a 1M-doc query block actually spend its time on the chip?

Breaks tiered scoring into its stages at wiki1m-like shapes (B=250 block,
H=500 hot rows, ~10 tiers, top-k over [B, 1M]) and times each in isolation.
Run on the real chip: python experiments/query_profile.py
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, iters=5):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
        jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main():
    print("devices:", jax.devices())
    rng = np.random.default_rng(0)
    d1 = 1_000_001
    b, h = 250, 500

    strip = jnp.asarray(rng.random((h, d1), np.float32))
    w_hot = jnp.asarray(rng.random((b, h), np.float32))
    scores = jnp.asarray(rng.random((b, d1), np.float32))

    t = timeit(jax.jit(lambda a, m: a @ m), w_hot, strip)
    print(f"hot matmul [B,{h}]@[{h},D]   : {t*1e3:8.2f} ms"
          f"  ({b/t:9.1f} q/s)")

    t = timeit(jax.jit(lambda m: jnp.where(m > 0, 1.0 + jnp.log(
        jnp.maximum(m, 1.0)), 0.0)), strip)
    print(f"strip weight_fn [H,D]        : {t*1e3:8.2f} ms")

    t = timeit(jax.jit(lambda s: jax.lax.top_k(s, 10)), scores)
    print(f"top_k k=10 [B,D]             : {t*1e3:8.2f} ms"
          f"  ({b/t:9.1f} q/s)")

    t = timeit(jax.jit(lambda s: jax.lax.top_k(s, 1000)), scores)
    print(f"top_k k=1000 [B,D]           : {t*1e3:8.2f} ms")

    # D2H fetch of one block's results (the tunnel's fixed latency).
    # Fresh device arrays per rep: jax.Array caches its fetched numpy
    # value, so re-fetching one array times a dict hit, not the wire
    # (same pitfall bench.transport_probe documents)
    pairs = []
    for _ in range(5):
        sc = jnp.asarray(rng.random((b, 10), np.float32))
        dn = jnp.asarray(rng.integers(0, d1, (b, 10)).astype(np.int32))
        jax.block_until_ready((sc, dn))
        pairs.append((sc, dn))
    t0 = time.perf_counter()
    for sc, dn in pairs:
        np.asarray(sc), np.asarray(dn)
    print(f"D2H fetch [B,10] x2          : {(time.perf_counter()-t0)/5*1e3:8.2f} ms")

    # a full tiered tfidf dispatch at synthetic 1M shapes
    from tpu_ir.ops.scoring import tfidf_topk_tiered
    from tpu_ir.search.layout import build_tiered_layout

    v, npairs = 200_000, 3_000_000
    df = rng.integers(1, 30, v).astype(np.int64)
    hot_ids = rng.choice(v, 400, replace=False)
    df[hot_ids] = rng.integers(10_000, 120_000, len(hot_ids))
    df = (df * (npairs / df.sum())).astype(np.int64)
    df = np.maximum(df, 1)
    indptr = np.concatenate([[0], np.cumsum(df)])
    total = int(indptr[-1])
    pair_doc = np.empty(total, np.int32)
    for tid in range(v):  # ascending docs per term
        n = df[tid]
        pair_doc[indptr[tid]:indptr[tid+1]] = np.sort(
            rng.choice(d1 - 1, n, replace=False) + 1) if n < 200_000 else \
            np.sort(rng.integers(1, d1, n))
    pair_tf = rng.integers(1, 20, total).astype(np.int32)
    lay = build_tiered_layout(pair_doc, pair_tf, df.astype(np.int32),
                              num_docs=d1 - 1)
    print("tiers:", [(td.shape) for td in lay.tier_docs])
    targs = (jnp.asarray(lay.hot_rank), lay.hot_device(),
             jnp.asarray(lay.tier_of), jnp.asarray(lay.row_of),
             tuple(jnp.asarray(a) for a in lay.tier_docs),
             tuple(jnp.asarray(a) for a in lay.tier_tfs),
             jnp.asarray(df.astype(np.int32)), jnp.int32(d1 - 1))
    q = jnp.asarray(rng.integers(0, v, (b, 8)).astype(np.int32))
    t = timeit(lambda q: tfidf_topk_tiered(q, *targs, num_docs=d1 - 1,
                                           k=10), q, iters=3)
    print(f"full tiered tfidf dispatch   : {t*1e3:8.2f} ms"
          f"  ({b/t:9.1f} q/s)")


if __name__ == "__main__":
    main()
