"""Multi-threaded serving soak under chaos: the long-running twin of
tests/test_serving.py's fast soak, driving mixed traffic through the
ServingFrontend (admission control + degradation ladder + circuit
breaker) while a deterministic fault plan injects hangs and device
losses on the score dispatch.

Reports the invariant counters as JSON and exits non-zero when any
serving invariant breaks (deadlock, untagged mismatch, vanished
request, unstructured error).

Usage:
  python experiments/soak_serving.py INDEX_DIR [options]
  python experiments/soak_serving.py --synthetic 2000 [options]

--synthetic N builds an N-doc corpus + index in a temp dir first (no
index needed on disk); see --help for the traffic/chaos knobs. Runs
hermetically on the CPU backend with an 8-virtual-device mesh, same
harness stance as the other experiments.
"""

import argparse
import json
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")
import jax._src.xla_bridge as xb

for _n in list(xb._backend_factories):
    if _n != "cpu":
        xb._backend_factories.pop(_n, None)

WORDS = ("salmon fish river bear honey fox dog run market investor "
         "asset bond stock season rain forest quick brown lazy "
         "mountain valley storm harbor signal").split()


def synthetic_index(n_docs: int, tmp: str) -> str:
    from tpu_ir.index.streaming import build_index_streaming

    corpus = os.path.join(tmp, "corpus.trec")
    with open(corpus, "w") as f:
        for i in range(n_docs):
            text = " ".join(WORDS[(i * 7 + j * 3) % len(WORDS)]
                            for j in range(4 + i % 9))
            f.write(f"<DOC>\n<DOCNO> S-{i:06d} </DOCNO>\n<TEXT>\n"
                    f"{text}\n</TEXT>\n</DOC>\n")
    index_dir = os.path.join(tmp, "idx")
    build_index_streaming([corpus], index_dir, k=1, num_shards=4,
                          batch_docs=500, chargram_ks=[])
    return index_dir


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("index_dir", nargs="?", default=None)
    ap.add_argument("--synthetic", type=int, default=None, metavar="DOCS",
                    help="build a synthetic index of DOCS documents "
                         "instead of reading one from disk")
    ap.add_argument("--layout", default="sparse",
                    choices=["auto", "dense", "sparse", "sharded"])
    ap.add_argument("--threads", type=int, default=16)
    ap.add_argument("--queries", type=int, default=2000)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--deadline", type=float, default=0.25)
    ap.add_argument("--concurrency", type=int, default=4)
    ap.add_argument("--queue-depth", type=int, default=8)
    ap.add_argument("--no-chaos", action="store_true",
                    help="skip fault injection (pure overload soak)")
    ap.add_argument("--faults", default=None, metavar="SPEC",
                    help="custom fault plan spec (default: the chaos "
                         "plan serving/soak.py ships)")
    ap.add_argument("--timeout", type=float, default=600.0)
    args = ap.parse_args()

    from tpu_ir.search import Scorer
    from tpu_ir.serving import DEFAULT_CHAOS_PLAN, ServingConfig, run_soak

    tmp = None
    try:
        if args.synthetic is not None:
            tmp = tempfile.mkdtemp(prefix="soak-serving-")
            index_dir = synthetic_index(args.synthetic, tmp)
        elif args.index_dir:
            index_dir = args.index_dir
        else:
            ap.error("give INDEX_DIR or --synthetic N")
        scorer = Scorer.load(index_dir, layout=args.layout)
        spec = (None if args.no_chaos
                else (args.faults or DEFAULT_CHAOS_PLAN))
        report = run_soak(
            scorer, threads=args.threads, queries=args.queries,
            seed=args.seed, fault_spec=spec,
            config=ServingConfig(
                max_concurrency=args.concurrency,
                max_queue=args.queue_depth, deadline_s=args.deadline,
                breaker_threshold=4, breaker_cooldown_s=0.2),
            timeout_s=args.timeout)
    finally:
        if tmp:
            shutil.rmtree(tmp, ignore_errors=True)
    print(json.dumps(report, indent=2, sort_keys=True, default=repr))
    ok = (report["errors"] == 0 and report["deadlocked"] == 0
          and report["untagged_mismatches"] == 0
          and report["served"] + report["shed"] == report["submitted"])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
