"""Differential multi-host fuzz: random corpora through a REAL 2-process
build (jax.distributed, 2x2 virtual CPU devices) vs the single-process
streaming build — artifacts must be byte-identical (fuzz_builds.py's
contract, extended across process boundaries: file slicing, host-side
allgathers, lockstep pass-2, shared position spills, process-0 store
assembly all under random corpora and batch sizes).

Usage: python experiments/fuzz_multihost.py [N_SEEDS] [FIRST_SEED]
"""

import os
import shutil
import socket
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")

import jax

jax.config.update("jax_platforms", "cpu")
import jax._src.xla_bridge as xb

for _n in list(xb._backend_factories):
    if _n != "cpu":
        xb._backend_factories.pop(_n, None)

import numpy as np

WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
import jax._src.xla_bridge as xb
for n in list(xb._backend_factories):
    if n != "cpu":
        xb._backend_factories.pop(n, None)

(coordinator, pid, index_dir, batch, k, positions, store,
 *paths) = sys.argv[1:]
from tpu_ir.parallel.multihost import init_distributed, build_index_multihost

init_distributed(coordinator, num_processes=2, process_id=int(pid))
build_index_multihost(list(paths), index_dir, k=int(k),
                      compute_chargrams=False, batch_docs=int(batch),
                      positions=positions == "1", store=store == "1")
print("worker", pid, "ok")
"""


def one_seed(seed: int) -> None:
    from fuzz_builds import make_corpus, require_identical

    from tpu_ir.index.streaming import build_index_streaming
    from tpu_ir.index.verify import verify_index

    rng = np.random.default_rng(10_000 + seed)
    tmp = tempfile.mkdtemp(prefix=f"fuzzmh{seed}-")
    try:
        paths, docs = make_corpus(rng, tmp)
        if not docs:
            return
        k = 1 if rng.integers(0, 4) else 2
        positions = bool(rng.integers(0, 2)) and k == 1
        store = bool(rng.integers(0, 2))
        batch = int(rng.integers(1, 6))

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        script = os.path.join(tmp, "worker.py")
        with open(script, "w") as f:
            f.write(WORKER)
        mh = os.path.join(tmp, "mh")
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = {**os.environ, "PYTHONPATH": root}
        procs = [
            subprocess.Popen(
                [sys.executable, script, f"127.0.0.1:{port}", str(pid),
                 mh, str(batch), str(k), "1" if positions else "0",
                 "1" if store else "0", *paths],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
                cwd=root, text=True)
            for pid in range(2)
        ]
        errs = []
        for p in procs:
            out, err = p.communicate(timeout=300)
            if p.returncode != 0:
                errs.append(err[-3000:])
        assert not errs, (seed, errs)

        # shard count = total device count (2 procs x 2 devices)
        ref = os.path.join(tmp, "ref")
        build_index_streaming(paths, ref, k=k, num_shards=4,
                              batch_docs=batch, compute_chargrams=False,
                              positions=positions, store=store)
        require_identical(ref, mh, f"mh-seed{seed}")
        assert verify_index(mh)["ok"], f"mh-seed{seed}: verify"
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
        jax.clear_caches()


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 10
    first = int(sys.argv[2]) if len(sys.argv) > 2 else 0
    for seed in range(first, first + n):
        one_seed(seed)
        print(f"mh seed {seed} ok", flush=True)
    print(f"ALL OK: {n} multihost seeds from {first}")


if __name__ == "__main__":
    main()
