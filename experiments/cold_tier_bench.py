"""Microbenchmark: XLA gather+scatter vs a fused Pallas kernel for the
cold-tier stage of tiered scoring, at 1M-doc shapes (VERDICT r1 item 7).

The XLA path (ops/scoring.py::_tiered_scores `do_tier`) materializes the
gathered [B, L, P_t] tier rows in HBM, then vmap-scatter-adds them into the
[B, D+1] accumulator. The Pallas candidate streams each (query, term)'s tier
row HBM->VMEM via a scalar-prefetched index map and scatters inside VMEM —
no [B, L, P_t] intermediate. The open question is whether Mosaic's dynamic
stores beat XLA's scatter lowering; this prints measured ms + q/s for both.

Run on the real chip:  python experiments/cold_tier_bench.py
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def xla_cold_tier(q_rows, in_tier, q_w, tdocs, ttfs, *, num_docs):
    """The production XLA path, lifted verbatim in shape/semantics from
    ops/scoring.py::_tiered_scores (tfidf weight curve)."""
    b = q_rows.shape[0]
    scores = jnp.zeros((b, num_docs + 1), jnp.float32)
    r = jnp.where(in_tier, q_rows, 0)
    docs = tdocs[r]                                  # [B, L, P_t]
    tfs = ttfs[r].astype(jnp.float32)
    w = jnp.where(tfs > 0, 1.0 + jnp.log(jnp.maximum(tfs, 1.0)), 0.0)
    w = w * q_w[..., None] * in_tier[..., None]
    slot = jnp.where((tfs > 0) & in_tier[..., None], docs, num_docs + 1)

    def add_cold(acc_q, slots_q, w_q):
        return acc_q.at[slots_q.ravel()].add(w_q.ravel(), mode="drop")

    return jax.vmap(add_cold)(scores, slot, w)


def pallas_cold_tier(q_rows, in_tier, q_w, tdocs, ttfs, *, num_docs,
                     interpret=False):
    """Fused: grid (B, L); the scalar-prefetched row index schedules each
    tier row's DMA; the kernel scatters into the query's [D+1] VMEM row."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, l = q_rows.shape
    v_t, p_t = tdocs.shape
    d1 = num_docs + 1

    safe_r = jnp.where(in_tier, q_rows, 0).astype(jnp.int32)
    w_eff = jnp.where(in_tier, q_w, 0.0)             # [B, L]

    def kernel(r_ref, w_ref, docs_ref, tfs_ref, out_ref):
        bb = pl.program_id(0)
        ll = pl.program_id(1)

        @pl.when(ll == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        w_q = w_ref[bb, ll]

        @pl.when(w_q != 0.0)
        def _():
            tfs = tfs_ref[0, 0, :].astype(jnp.float32)
            wv = jnp.where(tfs > 0,
                           1.0 + jnp.log(jnp.maximum(tfs, 1.0)), 0.0) * w_q

            def body(p, _):
                d = docs_ref[0, 0, p]
                out_ref[0, 0, d] = out_ref[0, 0, d] + wv[p]
                return 0

            jax.lax.fori_loop(0, p_t, body, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                       # safe_r, w_eff
        grid=(b, l),
        in_specs=[
            pl.BlockSpec((1, 1, p_t), lambda i, j, r, w: (r[i, j], 0, 0)),
            pl.BlockSpec((1, 1, p_t), lambda i, j, r, w: (r[i, j], 0, 0)),
        ],
        # singleton middle dim so the block's trailing two dims equal the
        # array's (same Mosaic constraint dodge as ops/pallas_scoring.py)
        out_specs=pl.BlockSpec((1, 1, d1), lambda i, j, r, w: (i, 0, 0)),
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, 1, d1), jnp.float32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(safe_r, w_eff, tdocs.reshape(v_t, 1, p_t), ttfs.reshape(v_t, 1, p_t))
    return out.reshape(b, d1)


def bench(fn, *args, warmup=1, iters=3, **kw):
    out = None
    for _ in range(warmup):
        out = fn(*args, **kw)
        jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args, **kw)
        jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters, out


def main():
    print("devices:", jax.devices())
    rng = np.random.default_rng(0)

    # representative wiki1m cold tier: cap 65536, a few dozen terms; a
    # query block of 250 with L=8 slots, ~1 slot in 4 landing in the tier
    for num_docs, v_t, p_t, b, l in [
        (1_000_000, 32, 65_536, 64, 8),
        (1_000_000, 32, 8_192, 64, 8),
        (100_000, 64, 8_192, 64, 8),
    ]:
        tdocs = np.zeros((v_t, p_t), np.int32)
        ttfs = np.zeros((v_t, p_t), np.int32)
        for r in range(v_t):
            n = rng.integers(p_t // 2, p_t)
            tdocs[r, :n] = np.sort(
                rng.choice(num_docs, size=n, replace=False) + 1)
            ttfs[r, :n] = rng.integers(1, 30, n)
        q_rows = rng.integers(0, v_t, (b, l)).astype(np.int32)
        in_tier = rng.random((b, l)) < 0.25
        q_w = rng.random((b, l)).astype(np.float32) + 0.1

        args = (jnp.asarray(q_rows), jnp.asarray(in_tier), jnp.asarray(q_w),
                jnp.asarray(tdocs), jnp.asarray(ttfs))
        xla_jit = jax.jit(partial(xla_cold_tier, num_docs=num_docs))
        t_x, out_x = bench(xla_jit, *args)
        print(f"D={num_docs} Vt={v_t} Pt={p_t} B={b} L={l}  "
              f"XLA: {t_x*1e3:8.2f} ms  ({b/t_x:8.1f} q/s)")
        try:
            interpret = jax.devices()[0].platform != "tpu"
            pal_jit = jax.jit(partial(
                pallas_cold_tier, num_docs=num_docs, interpret=interpret))
            t_p, out_p = bench(pal_jit, *args)
            ok = np.allclose(np.asarray(out_x), np.asarray(out_p),
                             rtol=1e-4, atol=1e-4)
            # interpret mode runs the kernel in Python — its timing says
            # nothing about hardware, so mark it unmistakably
            tag = "  [INTERPRET MODE — timing not meaningful]" \
                if interpret else f"  speedup={t_x/t_p:.2f}x"
            print(f"{'':38s}Pallas: {t_p*1e3:8.2f} ms  ({b/t_p:8.1f} q/s)"
                  f"  match={ok}{tag}")
        except Exception as e:  # noqa: BLE001 - report and continue
            print(f"{'':38s}Pallas FAILED: {type(e).__name__}: {e}")


if __name__ == "__main__":
    main()
