"""Traffic-shape models for the serving soaks and `tpu-ir serve-bench`.

Every soak before ISSUE 15 drove UNIFORM random queries at a flat
arrival rate — the one shape production traffic never has. Real query
logs are Zipf-distributed (a handful of head queries dominate; web
query-log studies measure exponents around 0.7-1.2) and arrive in
diurnal waves. This module makes that a first-class, SEEDED model:

- **query popularity**: each request draws a query RANK from a Zipf(s)
  distribution over a large query universe (the query-log shape: a
  handful of head queries soak up the volume; s = 0 is the uniform
  control — with a 100k-query universe, repeats are negligible). A
  rank deterministically materializes one request (text + scoring +
  rerank), so a repeated rank is a repeated REQUEST — the exact-hit
  cache's fuel, exactly as in a real log.
- **term draw**: each query's terms are drawn over the index's own
  vocabulary, ranked by document frequency (df descending), with term
  rank r drawn proportional to 1/r^s — head queries use head terms, so
  the head of the query distribution correlates with the head of the
  postings distribution (which is what makes the hot-postings
  residency hint pay).
- **query-length distribution**: seeded 1..3 terms per query (the
  legacy soak's shape), configurable.
- **request mix**: the soak's historical tfidf/bm25 split and ~25%
  rerank fraction, so Zipf rows stay comparable to the uniform history.
- **diurnal burst schedule** (optional): `pacing_scale(frac)` modulates
  inter-arrival pacing sinusoidally over the run — amplitude b means
  peak-rate traffic arrives ~(1+b)x faster than trough traffic.

Determinism: one `Workload` with one seed yields one query list and one
arrival schedule, so every soak remains replayable — the property the
whole chaos harness rides on. The draw itself is a cumulative-weight
inverse transform (no numpy RNG state), so it is stable across numpy
versions.
"""

from __future__ import annotations

import math
import random
from bisect import bisect_left

import numpy as np

# the legacy soak mix (soak.make_queries): preserved so a Zipf run
# differs from the uniform history ONLY in the term draw + arrivals
SCORINGS = ("tfidf", "bm25")
RERANK_CHOICES = (None, None, None, 25)
BURST_CYCLES = 2.0  # "diurnal" periods across one soak run


class Workload:
    """One seeded traffic model over a fixed term universe.

    `terms` must already be ranked most-popular-first (df descending for
    the from_scorer constructor); `skew` is the Zipf exponent s (0 =
    uniform). `burst` is the diurnal amplitude (0 = flat arrivals)."""

    # distinct queries the popularity draw ranges over: large enough
    # that the s=0 control virtually never repeats a request, small
    # enough that the rank CDF builds in microseconds
    UNIVERSE = 100_000

    def __init__(self, terms, *, skew: float = 0.0, seed: int = 0,
                 burst: float = 0.0, lengths=(1, 3), k: int = 10,
                 universe: int | None = None):
        self.terms = list(terms)
        if not self.terms:
            raise ValueError("workload needs a non-empty term universe")
        self.skew = float(skew)
        self.seed = int(seed)
        self.burst = float(burst)
        self.lengths = (int(lengths[0]), int(lengths[1]))
        self.k = int(k)
        self.universe = int(universe or self.UNIVERSE)
        # cumulative 1/r^s weights: draw by inverse transform (bisect
        # on one random float). s = 0 degenerates to the exact uniform
        # draw. One CDF over term ranks (within-query content), one
        # over query ranks (request popularity) — same exponent.
        self._term_cum, self._term_total = self._zipf_cdf(
            len(self.terms))
        self._rank_cum, self._rank_total = self._zipf_cdf(self.universe)
        self._rank_cache: dict[int, dict] = {}

    def _zipf_cdf(self, n: int) -> tuple[list, float]:
        ranks = np.arange(1, n + 1, dtype=np.float64)
        cum = np.cumsum(ranks ** (-self.skew))
        return cum.tolist(), float(cum[-1])

    @classmethod
    def from_scorer(cls, scorer, *, kind: str | None = None,
                    skew: float | None = None, seed: int = 0,
                    burst: float | None = None) -> "Workload | None":
        """Build the model from a loaded scorer's vocabulary, ranked by
        df descending (stable — ties keep vocabulary order, so the rank
        list is deterministic per generation). `kind`/`skew`/`burst`
        default to the TPU_IR_WORKLOAD* env knobs; returns None for the
        uniform kind — callers fall back to the legacy query maker,
        keeping historical soak rows bit-reproducible."""
        from ..utils import envvars

        kind = kind or envvars.get_choice("TPU_IR_WORKLOAD")
        if kind == "uniform":
            return None
        if skew is None:
            skew = envvars.get_float("TPU_IR_WORKLOAD_SKEW")
        if burst is None:
            burst = envvars.get_float("TPU_IR_WORKLOAD_BURST")
        terms = list(scorer.vocab.terms)
        if not terms:
            raise ValueError("scorer has an empty vocabulary")
        df = _df_ranking(scorer, len(terms))
        if df is not None:
            order = np.argsort(-df, kind="stable")
            terms = [terms[int(i)] for i in order]
        return cls(terms, skew=skew, seed=seed, burst=burst)

    # -- the draw ----------------------------------------------------------

    def draw_term(self, rng: random.Random) -> str:
        i = bisect_left(self._term_cum, rng.random() * self._term_total)
        return self.terms[min(i, len(self.terms) - 1)]

    def draw_rank(self, rng: random.Random) -> int:
        """One query-popularity rank (0-based) from the Zipf(s) draw
        over the query universe."""
        i = bisect_left(self._rank_cum, rng.random() * self._rank_total)
        return min(i, self.universe - 1)

    def query_for_rank(self, rank: int) -> dict:
        """The request query rank `rank` ALWAYS materializes to — one
        deterministic per-rank RNG seeds the length, term and
        scoring/rerank draws, so a repeated rank is a repeated exact
        request (text AND route flags), like a real query log."""
        cached = self._rank_cache.get(rank)
        if cached is not None:
            return dict(cached)
        rng = random.Random((self.seed + 1) * 0x9E3779B1 + rank)
        lo, hi = self.lengths
        req = {
            "text": " ".join(self.draw_term(rng)
                             for _ in range(rng.randint(lo, hi))),
            "scoring": rng.choice(SCORINGS),
            "rerank": rng.choice(RERANK_CHOICES),
            "k": self.k,
        }
        if len(self._rank_cache) < 4096:  # head ranks; bounded
            self._rank_cache[rank] = req
        return dict(req)

    def make_queries(self, n: int, seed: int | None = None) -> list[dict]:
        """The soak request list: same dict shape as soak.make_queries
        (text/scoring/rerank/k) — each request is the materialization
        of one Zipf-drawn query rank."""
        rng = random.Random(self.seed if seed is None else seed)
        return [self.query_for_rank(self.draw_rank(rng))
                for _ in range(int(n))]

    # -- the arrival schedule ----------------------------------------------

    def pacing_scale(self, frac: float) -> float:
        """Multiplier on the soak's inter-arrival pacing for the request
        at completed-fraction `frac` of the run: 1.0 everywhere when
        burst = 0; otherwise a sinusoid over BURST_CYCLES periods whose
        trough paces ~(1+burst)x slower than its peak — the compressed
        diurnal wave. Mean pacing stays near the flat schedule so a
        burst run's wall clock is comparable to its flat twin."""
        if self.burst <= 0.0:
            return 1.0
        # phased to START at the trough (wave(0) = 0): the run opens
        # calm and ramps into its first crest at frac (k+1/2)/C, so an
        # autoscaler A/B over this schedule measures reaction to the
        # WAVE, not to the thread-pool cold-start transient
        wave = 0.5 - 0.5 * math.cos(2.0 * math.pi * BURST_CYCLES
                                    * float(frac))
        # wave=1 (peak) -> 1/(1+b); wave=0 (trough) -> 1+b... normalized
        # around 1: peak arrivals are (1+b)x denser than trough arrivals
        return (1.0 + self.burst * (1.0 - wave)) / (1.0 + self.burst / 2.0)

    def is_peak(self, frac: float) -> bool:
        """True when the request at completed-fraction `frac` lands in a
        burst PEAK (arrivals denser than the flat schedule) — the window
        `burst_p99_ms` is measured over. Always False for a flat
        workload: a run with no wave has no peak to single out."""
        return self.burst > 0.0 and self.pacing_scale(frac) < 1.0

    def describe(self) -> dict:
        return {"kind": "zipf", "skew": self.skew, "seed": self.seed,
                "burst": self.burst, "terms": len(self.terms),
                "universe": self.universe,
                "lengths": list(self.lengths)}


def _df_ranking(scorer, vocab_size: int) -> np.ndarray | None:
    """The df vector for rank ordering, best-effort: the scorer's device
    df array when its length matches the vocabulary (tiered/sharded
    serving layouts keep the full-vocab df), else None (vocabulary
    order — still deterministic, just unranked)."""
    df = getattr(scorer, "df", None)
    if df is None:
        return None
    host = np.asarray(df).reshape(-1)
    if len(host) < vocab_size:
        return None
    return host[:vocab_size].astype(np.int64)


def resolve_workload(scorer, workload, *, seed: int = 0):
    """Normalize a soak's `workload` argument: None defers to the
    TPU_IR_WORKLOAD env knobs, "uniform"/"zipf" build from the scorer
    (skew/burst from env), a dict spec ({"kind", "skew", "burst"} —
    the serve-bench per-skew sweep) builds explicitly, a Workload
    instance passes through. Returns None for uniform."""
    if workload is None or isinstance(workload, str):
        return Workload.from_scorer(scorer, kind=workload, seed=seed)
    if isinstance(workload, dict):
        return Workload.from_scorer(scorer, seed=seed, **workload)
    return workload
