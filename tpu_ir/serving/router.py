"""Scatter-gather query router: one logical index over N shard workers.

The serving tier's distribution layer (ISSUE 10; ROADMAP 4 — the
"millions of users" topology). Every request fans out to S doc-shard
workers (shardset.py: full per-worker serving stacks over doc-range-
restricted scorers), each shard answers with its LOCAL top-k, and the
router merges exactly:

    request ─► admission ─► fan-out (S shards × R replicas) ─► exact merge
               (PR 2)        │ per-shard deadline                │ partial
                             │ hedged dispatch (tail at scale)   │ tagging
                             └ per-replica circuit breakers      ┘

**Exact merge.** Doc sharding makes the merge provably correct: a doc's
score depends only on its own postings plus GLOBAL statistics (df, N,
doc lengths), never on which shard holds it — and the workers' masked
layouts (layout.restrict_tiers) keep the kernel programs bit-identical
to the single-process scorer, so per-doc floats match exactly. The
host-side merge reproduces `lax.top_k` tie order (score desc, docid asc:
a stable sort over shard-ascending, rank-ordered lists), so merged
results are BIT-identical to the single-process Scorer — tie order
included (tests/test_router.py pins it across layouts × scorings).

**Tail tolerance ("The Tail at Scale").**
- *Hedged dispatch*: when a shard's primary replica exceeds
  max(TPU_IR_ROUTER_HEDGE_MS, the shard's trailing p99), the SAME
  request is sent to another replica and the first answer wins — a slow
  replica costs ~p99, not the deadline.
- *Failover*: a replica that FAILS (connection refused/reset, 5xx,
  shed) is immediately retried on the next replica within the shard
  deadline — a SIGKILLed worker costs one connect error, not an outage.
- *Per-replica breakers* (breaker.py, reused verbatim): consecutive
  failures stop the router from even trying a flapping replica; a
  half-open probe per cooldown detects recovery.
- *Partial results*: a shard that misses its deadline on EVERY replica
  is dropped from the merge and the response ships `partial=True` with
  `missing_shards` named — the PR-2 tagging ladder's fourth word. Every
  routed response is exactly one of full / degraded / partial /
  rejected (the distributed soak pins the taxonomy under chaos).

**Two-phase exact rerank.** `rerank=C` needs global candidates before
the cosine stage, so the router runs it in two RPCs: (1) per-shard BM25
top-C, merged to the global top-C — bit-identical to the single-process
stage 1; (2) `cosine_at` on every healthy shard at the merged candidate
list (each candidate's score comes from its owning shard; the kernel is
the same shared accumulation the production rerank traces), then the
final top-k over candidate order — the single-process tie rule.

Observability: `router.*` counters + `router.request`/`router.shard_rtt`
/`router.merge` histograms (declared in obs/registry.py), one querylog
entry per routed request recording the fan-out/hedge/partial decision,
and an aggregated `/healthz` (obs/server.register_router): shard →
replica liveness, breaker state, worker identity/generation, trailing
latency.
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import threading
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from dataclasses import dataclass

from .. import obs
from ..obs import disttrace
from ..obs import get_registry
from ..obs import trace as obs_trace
from ..utils import envvars
from .admission import AdmissionController, Overloaded
from .breaker import CircuitBreaker

logger = logging.getLogger(__name__)

# service levels ordered best-first; the routed response carries the
# WORST level any contributing shard served at
_LEVEL_ORDER = ("full", "no_rerank", "hot_only")


@dataclass(frozen=True)
class RouterConfig:
    """Routing knobs. None defaults defer to the TPU_IR_ROUTER_* env
    registry at construction (RUNBOOK §17 documents how to pick them)."""

    deadline_ms: float | None = None   # per-shard budget per request
    hedge_ms: float | None = None      # hedge-delay floor; 0 = no hedging
    connect_ms: float | None = None    # TCP connect timeout per attempt
    breaker_threshold: int = 3         # consecutive failures to open
    breaker_cooldown_s: float = 1.0    # open time before a probe
    max_concurrency: int = 16          # routed requests executing at once
    max_queue: int = 64                # routed requests allowed to wait
    rtt_window: int = 64               # trailing RTTs per shard (p99 src)
    health_ttl_s: float | None = None  # worker-health poll cache age
    # generation-keyed exact-hit result cache (ISSUE 15;
    # result_cache.py): a hit skips the whole fan-out — no RPC, no
    # hedge timer, no shard-RTT sample. None defers to
    # TPU_IR_CACHE_RESULTS; 0 disables.
    cache_entries: int | None = None


def merge_shard_topk(shard_hits, k: int) -> list:
    """EXACT top-k merge of per-shard hit lists.

    `shard_hits`: per-shard [(docid, score), ...] lists in per-shard
    rank order (score desc, docid asc — the kernel's own tie rule),
    ordered by ascending shard id; doc shards are contiguous ascending
    docid ranges, so a STABLE sort on score alone reproduces
    `lax.top_k`'s global tie order (lowest docid first) without ever
    comparing docids. Empty slots (docid <= 0 / score <= 0) are
    dropped, like the kernels' matched mask."""
    merged = [h for hits in shard_hits for h in hits
              if h[0] > 0 and h[1] > 0.0]
    merged.sort(key=lambda h: -h[1])  # Timsort is stable
    return merged[:k]


def merge_candidate_scores(cand: list, per_shard: dict,
                           ranges: list, k: int) -> list:
    """Stage-2 assembly of the two-phase rerank: each global candidate's
    cosine score comes from its OWNING shard's `cosine_at` response
    (per_shard: shard id -> [C] scores, aligned with `cand`), then the
    final top-k picks over CANDIDATE ORDER — `_topk_over_candidates`'s
    tie rule (lowest candidate position first), which a stable sort on
    score alone reproduces. Candidates whose owner is missing (a shard
    lost between the two phases) are dropped — the partial contract."""
    scored = []
    for pos, docid in enumerate(cand):
        if docid <= 0:
            continue
        owner = next((s for s, (lo, hi) in enumerate(ranges)
                      if lo <= docid <= hi), None)
        if owner is None or owner not in per_shard:
            continue
        scored.append((docid, per_shard[owner][pos]))
    scored.sort(key=lambda h: -h[1])
    return [(d, s) for d, s in scored[:k] if s > 0.0]


class _ShardStats:
    """Per-shard trailing latency window (the hedge-delay source) plus a
    round-robin cursor for replica selection. One tiny lock per shard —
    never held across IO."""

    def __init__(self, window: int):
        self._lock = threading.Lock()
        self._rtts: list = []
        self._window = window
        self._cursor = 0

    def observe(self, rtt_s: float) -> None:
        with self._lock:
            self._rtts.append(rtt_s)
            if len(self._rtts) > self._window:
                del self._rtts[: len(self._rtts) - self._window]

    def p99_s(self) -> float | None:
        with self._lock:
            if not self._rtts:
                return None
            s = sorted(self._rtts)
        return s[min(len(s) - 1, int(0.99 * (len(s) - 1)))]

    def next_cursor(self, n: int) -> int:
        with self._lock:
            self._cursor = (self._cursor + 1) % max(n, 1)
            return self._cursor


class Router:
    """The scatter-gather front door. Thread-safe; callers' threads run
    their own requests (admission bounds concurrency) while one owned
    pool runs the per-replica RPCs — sized so a full house of admitted
    requests can fan out and hedge without queuing behind each other.
    `close()` (or the context manager) shuts the pool down."""

    def __init__(self, index_dir: str, topology,
                 config: RouterConfig | None = None):
        from ..index import format as fmt
        from ..index import segments as seg
        from ..search.layout import shard_doc_ranges

        self.index_dir = index_dir
        # live index (ISSUE 12): the docno space, doc partition and
        # docno->docid mapping are PER GENERATION; a plain index dir is
        # the degenerate single-generation (0) case
        self._live_dir = index_dir if seg.is_live(index_dir) else None
        self._gen_infos: dict = {}
        self._gen_lock = threading.Lock()
        resolved_dir, self._gen0 = seg.resolve_serving(index_dir)
        self.config = cfg = config or RouterConfig()
        self._deadline_s = (cfg.deadline_ms if cfg.deadline_ms is not None
                            else envvars.get_float(
                                "TPU_IR_ROUTER_DEADLINE_MS")) / 1e3
        self._hedge_floor_s = (cfg.hedge_ms if cfg.hedge_ms is not None
                               else envvars.get_float(
                                   "TPU_IR_ROUTER_HEDGE_MS")) / 1e3
        self._connect_s = (cfg.connect_ms if cfg.connect_ms is not None
                           else envvars.get_float(
                               "TPU_IR_ROUTER_CONNECT_MS")) / 1e3
        self._health_ttl_s = (cfg.health_ttl_s
                              if cfg.health_ttl_s is not None
                              else envvars.get_float(
                                  "TPU_IR_ROUTER_HEALTH_TTL_S"))
        # topology: a ShardSet, a callable, or a static [shard][replica]
        # address grid — normalized to a callable re-read per request so
        # respawned workers (new ports) are picked up without plumbing.
        # An elastic topology (ISSUE 16) exposes TWO views: the router
        # DIALS `dispatchable()` (warming/draining/retired slots nulled
        # — a draining replica leaves the dispatch grid, its breaker's
        # probe rotation and the hedge p99 the instant its drain
        # begins) while health reporting reads the raw `addresses()`.
        self._lifecycle_fn = getattr(topology, "lifecycle", None)
        self._epoch_fn = getattr(topology, "epoch", None)
        if callable(topology):
            self._topology = topology
            self._full_topology = topology
        elif hasattr(topology, "addresses"):
            self._topology = getattr(topology, "dispatchable",
                                     topology.addresses)
            self._full_topology = topology.addresses
        else:
            static = [list(row) for row in topology]
            self._topology = lambda: static
            self._full_topology = self._topology
        grid = self._full_topology()
        self.num_shards = len(grid)
        if self.num_shards < 1:
            raise ValueError("topology has no shards")
        meta = fmt.IndexMetadata.load(resolved_dir)
        self.num_docs = meta.num_docs
        self._ranges = shard_doc_ranges(meta.num_docs, self.num_shards)
        self._gen_infos[self._gen0] = {
            "dir": resolved_dir, "num_docs": meta.num_docs,
            "ranges": self._ranges, "mapping": None}
        self.admission = AdmissionController(cfg.max_concurrency,
                                             cfg.max_queue)
        # the fan-out result cache (ISSUE 15): exact-hit, keyed by
        # normalized terms + route flags + the newest generation this
        # router has seen WIN a merge — a rolling swap moves the key
        # space, making every old-generation entry unreachable
        from .result_cache import ResultCache, resolve_capacity

        cap = resolve_capacity(cfg.cache_entries)
        self.cache = ResultCache(cap, name="router") if cap > 0 else None
        # the cache generation deliberately starts at 0 and converges
        # from RESPONSES (and note_generation), not from the newest
        # servable manifest: a fleet pinned to an older generation (the
        # upgrade soak's pre-swap phase) must still cache — the cache
        # follows what the workers actually serve, never the filesystem
        self._breakers: dict = {}
        self._breakers_lock = threading.Lock()
        self._stats = [_ShardStats(cfg.rtt_window)
                       for _ in range(self.num_shards)]
        # sized for a full admission house fanning out AND hedging: the
        # request threads are the callers', only RPC attempts run here
        self._pool = ThreadPoolExecutor(
            max_workers=max(4, cfg.max_concurrency * self.num_shards * 2),
            thread_name_prefix="tpu-ir-router")
        self._closed = False
        self._health_lock = threading.Lock()
        self._health_cache: tuple | None = None  # (monotonic, payload)
        self._health_polling = False
        from ..obs.server import register_router

        register_router(self)
        # waterfall lane label for this process's span records — best
        # effort (an in-process worker sharing the router's process
        # relabels; subprocess fleets, the real topology, never collide)
        disttrace.set_service("router")

    # -- plumbing ----------------------------------------------------------

    def _breaker(self, shard: int, replica: int) -> CircuitBreaker:
        key = (shard, replica)
        with self._breakers_lock:
            b = self._breakers.get(key)
            if b is None:
                b = self._breakers[key] = CircuitBreaker(
                    self.config.breaker_threshold,
                    self.config.breaker_cooldown_s)
            return b

    def _gen_info(self, gen: int) -> dict | None:
        """The per-generation view (servable dir, num_docs, doc-range
        partition, lazy docno->docid mapping), or None when the
        generation cannot be resolved (its manifest was gc'd, or a
        worker reported a generation this router's filesystem view
        doesn't know) — the caller treats its responses as lost rather
        than 500ing the request. Looked up once per new generation a
        worker reports; the load happens OUTSIDE the lock (manifest +
        metadata IO must not stall concurrent requests on other
        generations)."""
        with self._gen_lock:
            info = self._gen_infos.get(gen)
        if info is not None:
            return info
        from ..index import format as fmt
        from ..index import segments as seg
        from ..search.layout import shard_doc_ranges

        src = self._live_dir or self.index_dir
        try:
            resolved, _ = seg.resolve_serving(src, gen if self._live_dir
                                              else None)
            meta = fmt.IndexMetadata.load(resolved)
        except (OSError, ValueError) as e:
            logger.warning("cannot resolve index generation %s: %r",
                           gen, e)
            return None
        info = {"dir": resolved, "num_docs": meta.num_docs,
                "ranges": shard_doc_ranges(meta.num_docs,
                                           self.num_shards),
                "mapping": None}
        with self._gen_lock:
            return self._gen_infos.setdefault(gen, info)

    def _mapping_loaded(self, gen: int | None = None):
        info = self._gen_info(self._gen0 if gen is None else gen)
        if info is None:  # winners always resolved; belt-and-braces
            raise RuntimeError(f"generation {gen} is not resolvable")
        if info["mapping"] is None:
            from ..collection import DocnoMapping
            from ..index import format as fmt

            # benign race: two loaders both read, last reference wins
            info["mapping"] = DocnoMapping.load(
                os.path.join(info["dir"], fmt.DOCNOS))
        return info["mapping"]

    def _post(self, addr: str, path: str, payload: dict,
              timeout_s: float, headers: dict | None = None) -> dict:
        """One HTTP RPC attempt via the SHARED worker-RPC client
        (shardset.rpc_post — one framing for router fan-out and
        rolling swaps); raises on any failure (the caller's breaker
        records the verdict). The socket timeout bounds connect AND
        read, so a SIGKILLed worker costs one refused connect and a
        hung one at most `timeout_s`."""
        from .shardset import rpc_post

        return rpc_post(addr, path, payload, timeout_s, headers=headers)

    def _call_replica(self, shard: int, replica: int, addr: str,
                      path: str, payload: dict, timeout_s: float,
                      ctx=None):
        """One replica attempt with its breaker verdict + RTT sample.
        Returns (ok, data_or_error). `ctx` is this attempt's derived
        TraceContext (ISSUE 18): the worker adopts it off the
        traceparent header, its span batch rides back on the response's
        `_trace` key, and the attempt span (recorded at submit) gets
        its true duration + verdict annotated here."""
        breaker = self._breaker(shard, replica)
        allowed, is_probe = breaker.allow_device()
        if not allowed:
            if ctx is not None:
                disttrace.annotate(ctx.trace_id, ctx.span_id,
                                   ok=False, error="breaker_open")
            return False, "breaker_open"
        headers = ({"traceparent": ctx.to_header()}
                   if ctx is not None else None)
        t0 = time.perf_counter()
        try:
            data = self._post(addr, path, payload, timeout_s,
                              headers=headers)
        except BaseException as e:  # noqa: BLE001 — every failure is a
            # replica verdict here (refused, reset, timeout, 5xx, shed)
            if breaker.record_failure(is_probe=is_probe):
                get_registry().incr("router.breaker_opened")
            get_registry().incr("router.replica_failed")
            if ctx is not None:
                disttrace.annotate(
                    ctx.trace_id, ctx.span_id,
                    dur_ms=(time.perf_counter() - t0) * 1e3,
                    ok=False, error=repr(e))
            return False, repr(e)
        rtt = time.perf_counter() - t0
        if ctx is not None:
            disttrace.annotate(ctx.trace_id, ctx.span_id,
                               dur_ms=rtt * 1e3, ok=True)
            if isinstance(data, dict):
                # live stitching: fold the worker's span batch into the
                # local store (runs on a pool thread — the store locks)
                disttrace.ingest_remote(data.pop("_trace", None))
        breaker.record_success(is_probe=is_probe)
        # a replica that went DRAINING while this call was in flight
        # still answers (drain-not-drop), but its RTT must not feed the
        # hedge estimate: a drain is membership change, not a slow peer
        if not self._replica_draining(shard, replica):
            self._stats[shard].observe(rtt)
        if obs.enabled():
            get_registry().observe("router.shard_rtt", rtt)
        return True, data

    def _replica_draining(self, shard: int, replica: int) -> bool:
        if self._lifecycle_fn is None:
            return False
        try:
            life = self._lifecycle_fn()
            return life[shard][replica] == "draining"
        except (IndexError, TypeError):
            return False

    def reset_breaker(self, shard: int, replica: int) -> None:
        """Forget one replica's breaker history — the autoscaler calls
        this when a scale-up REUSES a retired slot index: the fresh
        warm worker must not inherit whatever state the slot's previous
        occupant earned (breaker.reset() keeps the object in place so
        in-flight verdicts still land where later requests read)."""
        with self._breakers_lock:
            b = self._breakers.get((shard, replica))
        if b is not None:
            b.reset()

    def _replica_order(self, shard: int, avail: list) -> list:
        """Replica try-order for one request over the ADDRESSED replica
        indices (`avail` — a grid row may carry None placeholders for
        slots with no worker): round-robin start (spread load over
        replicas), open-breaker replicas pushed to the end (still
        listed — with everything open, trying one is how the half-open
        probe path re-discovers a recovered worker)."""
        if not avail:
            return []
        start = self._stats[shard].next_cursor(len(avail))
        order = [avail[(start + i) % len(avail)]
                 for i in range(len(avail))]
        open_state = []
        closed = []
        for r in order:
            b = self._breaker(shard, r)
            (closed if b.state == "closed" else open_state).append(r)
        return closed + open_state

    def _hedge_delay_s(self, shard: int) -> float:
        """THIS shard's hedge delay: max(floor, the shard's own
        trailing p99), capped at the deadline. Per shard by design — a
        globally-pooled delay would let one slow shard defeat the tail
        cap on every fast one."""
        if self._hedge_floor_s <= 0.0:
            return float("inf")  # hedging disabled
        p99 = self._stats[shard].p99_s()
        return min(max(self._hedge_floor_s, p99 or 0.0),
                   self._deadline_s)

    # -- the fan-out -------------------------------------------------------

    def _fanout(self, path: str, payload_of, shards: list) -> dict:
        """Run one RPC against every shard in `shards` concurrently,
        with failover + hedging per shard. Returns {shard: (data,
        hedges_fired)} for the shards that answered within the deadline.

        All futures are submitted from THIS (caller's) thread — pool
        tasks never submit to the pool, so a saturated pool delays but
        cannot deadlock."""
        grid = self._topology()
        deadline = time.monotonic() + self._deadline_s
        hedge_delay = {s: self._hedge_delay_s(s) for s in shards}
        # the request's trace context, captured on THIS (caller's)
        # thread — pool threads never see the request thread-local, so
        # per-attempt child contexts derive from this explicit handle
        ctx = disttrace.current()
        tid = ctx.trace_id if ctx is not None else None

        class _ShardJob:
            __slots__ = ("order", "next_i", "futs", "t0", "hedged",
                         "result", "hedges")

            def __init__(self):
                self.order: list = []
                self.next_i = 0
                self.futs: list = []   # (replica, fut, is_hedge, span)
                self.t0 = time.monotonic()
                self.hedged = False
                self.result = None
                self.hedges = 0

        jobs: dict[int, _ShardJob] = {}
        for s in shards:
            job = _ShardJob()
            row = grid[s] if s < len(grid) else []
            # order carries GRID indices of addressed replicas only —
            # a None placeholder slot is never dialed, and breaker /
            # health numbering stays aligned with the grid
            avail = [i for i, a in enumerate(row) if a]
            job.order = self._replica_order(s, avail)
            jobs[s] = job
            self._submit_next(s, job, grid, path, payload_of(s),
                              deadline, is_hedge=False, ctx=ctx)

        while True:
            now = time.monotonic()
            pending = []
            for s, job in jobs.items():
                if job.result is not None:
                    continue
                # harvest completed attempts: first success wins; a
                # failure immediately triggers the next replica
                # (failover), distinct from the timed hedge below
                still = []
                for replica, fut, is_hedge, sid in job.futs:
                    if not fut.done():
                        still.append((replica, fut, is_hedge, sid))
                        continue
                    ok, data = fut.result()
                    if ok and job.result is None:
                        job.result = data
                        if is_hedge:
                            get_registry().incr("router.hedge_won")
                        # the trace records WHICH attempt served the
                        # response — the hedge post-mortem's first
                        # question
                        disttrace.annotate(tid, sid, outcome="won",
                                           hedge=is_hedge)
                    elif ok:
                        # answered correctly, but another attempt had
                        # already won this shard — the dropped loser
                        disttrace.annotate(tid, sid, outcome="lost",
                                           hedge=is_hedge)
                    else:
                        disttrace.annotate(tid, sid, outcome="failed",
                                           hedge=is_hedge)
                job.futs = still
                if job.result is not None:
                    continue
                if not job.futs and job.next_i < len(job.order):
                    # every in-flight attempt failed: fail over now
                    self._submit_next(s, job, grid, path, payload_of(s),
                                      deadline, is_hedge=False, ctx=ctx)
                elif (not job.hedged and job.futs
                        and now - job.t0 >= hedge_delay[s]
                        and job.next_i < len(job.order)):
                    # the primary is slow, not dead: hedge to the next
                    # replica and let the fastest answer win
                    job.hedged = True
                    job.hedges += 1
                    get_registry().incr("router.hedge_fired")
                    self._submit_next(s, job, grid, path, payload_of(s),
                                      deadline, is_hedge=True, ctx=ctx)
                pending.extend(f for _, f, _, _ in job.futs)
            unresolved = [s for s, j in jobs.items() if j.result is None]
            if not unresolved or now >= deadline:
                break
            if not pending:
                # nothing in flight and nothing left to try: the shard
                # is lost for this request, no point burning the clock
                if all(jobs[s].next_i >= len(jobs[s].order)
                       and not jobs[s].futs for s in unresolved):
                    break
                continue
            # wake on the next interesting instant: a completion, the
            # earliest pending hedge deadline, or the shard deadline
            next_hedge = min(
                (jobs[s].t0 + hedge_delay[s] for s in unresolved
                 if not jobs[s].hedged
                 and jobs[s].next_i < len(jobs[s].order)),
                default=deadline)
            wait(pending, timeout=max(
                0.001, min(next_hedge, deadline) - time.monotonic()),
                return_when=FIRST_COMPLETED)
        if tid is not None:
            # attempts still in flight when the fan-out returns: the
            # winner made them moot (cancelled — the response will be
            # silently dropped) or the deadline expired under them (the
            # "why did this response go partial" answer)
            for s, job in jobs.items():
                for replica, fut, is_hedge, sid in job.futs:
                    disttrace.annotate(
                        tid, sid, hedge=is_hedge,
                        outcome=("cancelled" if job.result is not None
                                 else "deadline"))
        return {s: (j.result, j.hedges) for s, j in jobs.items()
                if j.result is not None}

    def _submit_next(self, shard: int, job, grid, path: str,
                     payload: dict, deadline: float,
                     *, is_hedge: bool, ctx=None) -> None:
        if job.next_i >= len(job.order):
            return
        replica = job.order[job.next_i]
        job.next_i += 1
        addr = grid[shard][replica]
        timeout_s = max(deadline - time.monotonic(), 1e-3)
        # connect timeout never exceeds the attempt budget, and a dead
        # host must fail fast enough to leave room for failover
        timeout_s = min(timeout_s, self._deadline_s)
        # the attempt span records AT SUBMIT (duration + verdict
        # annotated on completion): an attempt cancelled mid-flight
        # must still appear in the waterfall, or the trace under-counts
        # the fan-out it claims to explain
        actx = disttrace.child(ctx)
        sid = None
        if actx is not None:
            sid = disttrace.add_span(
                actx.trace_id, f"rpc.{path}", span_id=actx.span_id,
                parent_id=actx.parent_id,
                attrs={"shard": shard, "replica": replica,
                       "addr": addr, "hedge": is_hedge})
        fut = self._pool.submit(self._call_replica, shard, replica,
                                addr, path, payload, timeout_s, actx)
        job.futs.append((replica, fut, is_hedge, sid))

    # -- the request path --------------------------------------------------

    def search(self, text: str, *, k: int = 10, scoring: str = "tfidf",
               rerank: int | None = None,
               return_docids: bool = True):
        """Serve one query across the shard fleet. Returns a
        SearchResult tagged with the routed taxonomy (level, degraded,
        partial + shards_ok/missing_shards/hedges), or raises Overloaded
        (router admission shed, or no shard answered at all). Phrase
        queries score on the host against positions the workers don't
        fan out — route them to a single-process frontend instead."""
        if '"' in text:
            raise ValueError("phrase queries are not routable; serve "
                             "them through a single-process frontend")
        t0 = time.perf_counter()
        get_registry().incr("router.requests")
        # exact-hit cache, ahead of admission and the fan-out (ISSUE
        # 15): a request that will be served from cache never takes an
        # admission slot, never dials a worker, and never arms a hedge
        # timer — and because no replica RPC runs, the per-shard
        # trailing-p99 hedge estimate only ever sees real round trips
        cache_key = self._cache_key(text, k=k, scoring=scoring,
                                    rerank=rerank)
        if cache_key is not None:
            t_lookup = time.perf_counter()
            entry = self.cache.get(cache_key)
            self._observe("cache.lookup", t_lookup)
            if entry is not None:
                res = self._from_cache(entry, return_docids=return_docids)
                res.trace_id = None
                self._observe("router.request", t0)
                self._count_served(res)
                disttrace.slo_record(
                    res.level, (time.perf_counter() - t0) * 1e3,
                    classification=self.classify(res))
                self._querylog(text, res, k=k, scoring=scoring,
                               rerank=rerank, t0=t0, cached=True)
                return res
        # distributed tracing (ISSUE 18): the trace is minted HERE, at
        # router admission — the one process that sees the whole
        # request — and installed thread-locally so the fan-out's
        # per-attempt child contexts and the root-close keep/drop
        # verdict all key off it
        ctx = disttrace.mint()
        with disttrace.use(ctx), \
                obs_trace("request", scoring=scoring, router=True) as root:
            try:
                admit = self.admission.admit(
                    queue_timeout_s=self._deadline_s)
                with obs_trace("admission_wait"):
                    admit.__enter__()
            except Overloaded:
                get_registry().incr("router.shed")
                self._observe("router.request", t0)
                root.set("shed", True)
                disttrace.slo_record(
                    "shed", (time.perf_counter() - t0) * 1e3,
                    ok=False, classification="shed")
                raise
            try:
                res = self._route(text, k=k, scoring=scoring,
                                  rerank=rerank)
            except Overloaded:
                # the no-shard-answered shed is a rejection like any
                # other: it must land in router.shed and the request
                # histogram, or the declared counter conservation
                # (requests == served_* + shed) drifts exactly during
                # an outage window
                get_registry().incr("router.shed")
                self._observe("router.request", t0)
                root.set("shed", True)
                disttrace.slo_record(
                    "shed", (time.perf_counter() - t0) * 1e3,
                    ok=False, classification="shed")
                raise
            finally:
                admit.__exit__(None, None, None)
            root.set("partial", res.partial)
            root.set("level", res.level)
            root.set("degraded", bool(res.degraded))
            root.set("hedges", int(res.hedges))
        res.trace_id = ctx.trace_id if ctx is not None else None
        if self.cache is not None:
            # follow the fleet: the newest generation to win a merge
            # moves the cache's key space (old entries go unreachable)
            self.cache.bump_generation(int(res.generation))
            if cache_key is not None and self.classify(res) == "full":
                # only clean full-route responses are frozen — partial
                # and degraded responses are weather; stored as raw
                # docnos so one entry serves both docid flavors
                self.cache.put(
                    (cache_key[0], int(res.generation)) + cache_key[2:],
                    {"hits": tuple(res), "shards_ok": res.shards_ok,
                     "generation": int(res.generation),
                     "level": res.level},
                    generation=int(res.generation))
        if return_docids and len(res):
            # the docno->docid mapping of the generation that ANSWERED
            # — a gen-A mapping applied to gen-B docnos would silently
            # name the wrong documents across a rolling swap
            mapping = self._mapping_loaded(res.generation)
            res[:] = [(mapping.get_docid(int(d)), s) for d, s in res]
        self._observe("router.request", t0)
        self._count_served(res)
        disttrace.slo_record(res.level, (time.perf_counter() - t0) * 1e3,
                             classification=self.classify(res))
        self._querylog(text, res, k=k, scoring=scoring, rerank=rerank,
                       t0=t0)
        return res

    def _cache_key(self, text: str, *, k: int, scoring: str,
                   rerank: int | None) -> tuple | None:
        """The router-side exact-hit key, or None when uncacheable
        (cache off; glob/fuzzy operators — their expansion is vocab-
        dependent and must not collide with literal terms). Terms are
        whitespace-normalized only (the router has no analyzer; weaker
        normalization costs missed hits, never wrong ones); slot 1 is
        the newest generation this router has seen win — the lookup
        face of invalidation-by-key."""
        from .result_cache import cacheable_text, normalize_terms

        if self.cache is None or not cacheable_text(text):
            return None
        return (normalize_terms(text), self.cache.generation(),
                int(k), scoring, rerank)

    def _from_cache(self, entry: dict, *, return_docids: bool):
        """Rebuild a SearchResult from a stored full-route payload —
        bit-identical to the miss path by construction (the stored hits
        ARE a miss path's merge; the docno->docid mapping is
        deterministic per generation)."""
        from ..search.scorer import SearchResult

        res = SearchResult((int(d), float(s)) for d, s in entry["hits"])
        res.generation = entry["generation"]
        res.level = entry["level"]
        res.shards_ok = tuple(entry["shards_ok"])
        res.missing_shards = ()
        res.partial = False
        res.degraded = False
        res.hedges = 0
        if return_docids and len(res):
            mapping = self._mapping_loaded(res.generation)
            res[:] = [(mapping.get_docid(int(d)), s) for d, s in res]
        return res

    def note_generation(self, generation: int) -> int:
        """Tell the router a newer index generation is being rolled out
        (the rolling-swap driver calls this the moment every replica
        confirmed): the cache key space moves immediately instead of
        waiting for the first new-generation response to win a merge —
        without it, a head query cached pre-swap could keep answering
        from the old (still known, correctly tagged) generation until
        traffic happened to reveal the new one. Returns purged entry
        count; no-op without a cache."""
        if self.cache is None:
            return 0
        return self.cache.bump_generation(int(generation))

    def _winning_generation(self, got: dict) -> tuple[int, dict, bool]:
        """Split one fan-out's responses by the index generation each
        worker reported and pick the winner: most responding shards,
        ties to the NEWEST generation. Docnos, doc ranges and scores
        are only comparable within one generation — merging across two
        corpus snapshots would return docids from neither — so the
        losers are discarded and tagged missing (partial). A candidate
        generation this router cannot RESOLVE (manifest gc'd, foreign
        report) is skipped the same way — its responses are lost, not
        a request-killing error; with no resolvable candidate at all
        the request sheds structurally. Returns (generation, winning
        {shard: (data, hedges)}, mixed?)."""
        by_gen: dict[int, dict] = {}
        for s, (d, h) in got.items():
            by_gen.setdefault(int(d.get("generation", 0)), {})[s] = (d, h)
        mixed = len(by_gen) > 1
        for gen in sorted(by_gen, key=lambda g: (len(by_gen[g]), g),
                          reverse=True):
            if self._gen_info(gen) is not None:
                return gen, by_gen[gen], mixed
        raise Overloaded("no_resolvable_generation",
                         queue_depth=self.admission.queue_depth(),
                         level="shed")

    def _route(self, text: str, *, k: int, scoring: str,
               rerank: int | None):
        all_shards = list(range(self.num_shards))
        if rerank:
            return self._route_rerank(text, k=k, candidates=rerank,
                                      shards=all_shards)
        payload = {"text": text, "k": k, "scoring": scoring}
        got = self._fanout("search", lambda s: payload, all_shards)
        if not got:
            get_registry().incr("router.shard_lost", self.num_shards)
            raise Overloaded("no_healthy_shards",
                             queue_depth=self.admission.queue_depth(),
                             level="shed")
        gen, winners, mixed = self._winning_generation(got)
        if mixed:
            get_registry().incr("router.mixed_generation")
        t_merge = time.perf_counter()
        hits = merge_shard_topk(
            [winners[s][0]["hits"] for s in sorted(winners)], k)
        self._observe("router.merge", t_merge)
        return self._assemble(hits, winners, all_shards, gen=gen)

    def _route_rerank(self, text: str, *, k: int, candidates: int,
                      shards: list):
        """Two-phase exact rerank (module docstring): BM25 top-C per
        shard -> global top-C -> cosine_at on every phase-1-healthy
        shard -> final top-k over candidate order."""
        p1 = {"text": text, "k": candidates, "scoring": "bm25"}
        got = self._fanout("search", lambda s: p1, shards)
        if not got:
            get_registry().incr("router.shard_lost", self.num_shards)
            raise Overloaded("no_healthy_shards",
                             queue_depth=self.admission.queue_depth(),
                             level="shed")
        gen, winners, mixed = self._winning_generation(got)
        cand_hits = merge_shard_topk(
            [winners[s][0]["hits"] for s in sorted(winners)], candidates)
        # the fixed candidate-matrix width the single-process kernel
        # would have used: pad to C with empty slots (docid 0)
        cand = [d for d, _ in cand_hits]
        cand += [0] * (candidates - len(cand))
        p2 = {"text": text, "cand": cand}
        got2 = self._fanout("cosine_at", lambda s: p2, sorted(winners))
        # phase 2 must answer from the SAME generation phase 1 won —
        # the candidate list is gen-local docnos; a worker that swapped
        # between phases would score the wrong documents' ids
        got2 = {s: v for s, v in got2.items()
                if int(v[0].get("generation", 0)) == gen}
        if mixed:
            get_registry().incr("router.mixed_generation")
        if not got2:
            get_registry().incr("router.shard_lost", len(got))
            raise Overloaded("no_healthy_shards",
                             queue_depth=self.admission.queue_depth(),
                             level="shed")
        t_merge = time.perf_counter()
        hits = merge_candidate_scores(
            cand, {s: d["scores"] for s, (d, _) in got2.items()},
            self._gen_info(gen)["ranges"], k)
        self._observe("router.merge", t_merge)
        # a shard must survive BOTH phases to count as contributing
        merged_meta = {s: winners[s] for s in got2}
        res = self._assemble(hits, merged_meta, shards, gen=gen)
        res.hedges += sum(h for _, h in got2.values())
        return res

    def _assemble(self, hits: list, got: dict, shards: list,
                  gen: int | None = None):
        from ..search.scorer import SearchResult

        gen = self._gen0 if gen is None else gen
        ranges = self._gen_info(gen)["ranges"]
        res = SearchResult((int(d), float(s)) for d, s in hits)
        res.generation = gen
        ok = tuple(sorted(got))
        missing = tuple(s for s in shards if s not in got)
        # trailing shards past num_docs own an empty range — their
        # absence loses no documents and must not tag the response
        missing = tuple(s for s in missing
                        if ranges[s][0] <= ranges[s][1])
        res.shards_ok = ok
        res.missing_shards = missing
        res.partial = bool(missing)
        if missing:
            get_registry().incr("router.shard_lost", len(missing))
        res.hedges = sum(h for _, h in got.values())
        res.degraded = any(d.get("degraded") for d, _ in got.values())
        levels = [d.get("level", "full") for d, _ in got.values()]
        res.level = max(levels, key=lambda lv: _LEVEL_ORDER.index(lv)
                        if lv in _LEVEL_ORDER else len(_LEVEL_ORDER))
        return res

    # -- accounting / introspection ----------------------------------------

    @staticmethod
    def classify(res) -> str:
        """The routed-response taxonomy (exactly one of): partial beats
        degraded beats full; rejections raise and never reach here."""
        if res.partial:
            return "partial"
        if res.degraded or res.level != "full":
            return "degraded"
        return "full"

    def _count_served(self, res) -> None:
        get_registry().incr(f"router.served_{self.classify(res)}")

    @staticmethod
    def _observe(name: str, t0: float) -> None:
        if obs.enabled():
            get_registry().observe(name, time.perf_counter() - t0)

    def _querylog(self, text: str, res, *, k: int, scoring: str,
                  rerank: int | None, t0: float,
                  cached: bool = False) -> None:
        from ..obs import querylog

        entry = {
            "router": True,
            "cached": cached,
            "query_hash": querylog.query_hash(text.split()),
            "k": k, "scoring": scoring, "rerank": rerank,
            "level": res.level, "degraded": bool(res.degraded),
            "generation": int(res.generation),
            "partial": bool(res.partial),
            "shards_ok": list(res.shards_ok),
            "missing_shards": list(res.missing_shards),
            "hedges": int(res.hedges),
            "total_ms": round((time.perf_counter() - t0) * 1e3, 3),
        }
        # the slow-query capture's join key into its distributed
        # waterfall (ISSUE 18): `tpu-ir querylog --trace <id>`
        tid = getattr(res, "trace_id", None)
        if tid:
            entry["trace_id"] = tid
        if not querylog.redacted():
            entry["text"] = text
        querylog.record(entry)

    def health_summary(self) -> dict:
        """The aggregated shard-health view /healthz serves (TTL-cached:
        one poll sweep per TPU_IR_ROUTER_HEALTH_TTL_S, not per scrape):
        per shard, each replica's liveness + breaker + the worker's own
        reported identity (shard/replica/generation/doc_range) and
        control-plane state."""
        with self._health_lock:
            cached = self._health_cache
            if (cached is not None
                    and time.monotonic() - cached[0] < self._health_ttl_s):
                return cached[1]
            if self._health_polling:
                # re-entrancy guard: when router and workers share one
                # process (in-process workers in tests), a poll sweep's
                # GET /healthz lands back here through the worker's own
                # handler — answer shallow instead of sweeping forever
                return {"num_shards": self.num_shards,
                        "in_progress": True}
            self._health_polling = True
        try:
            return self._health_sweep()
        finally:
            with self._health_lock:
                self._health_polling = False

    def _health_sweep(self) -> dict:
        grid = self._full_topology()
        life = None
        if self._lifecycle_fn is not None:
            try:
                life = self._lifecycle_fn()
            except Exception:  # noqa: BLE001 — health must not 500
                life = None
        shards = []
        for s in range(self.num_shards):
            row = grid[s] if s < len(grid) else []
            replicas = []
            for r, addr in enumerate(row):
                item = {"replica": r, "addr": addr,
                        "breaker": self._breaker(s, r).snapshot()}
                if life is not None and s < len(life) \
                        and r < len(life[s]):
                    item["lifecycle"] = life[s][r]
                item.update(self._poll_worker_health(addr))
                replicas.append(item)
            p99 = self._stats[s].p99_s()
            hedge = self._hedge_delay_s(s)
            shards.append({
                "shard": s,
                "doc_range": list(self._ranges[s]),
                "rtt_p99_ms": (round(p99 * 1e3, 3)
                               if p99 is not None else None),
                "hedge_delay_ms": (round(hedge * 1e3, 3)
                                   if hedge != float("inf") else None),
                "replicas": replicas,
            })
        with self._gen_lock:
            gens = sorted(self._gen_infos)
        payload = {"num_shards": self.num_shards,
                   "membership_epoch": (self._epoch_fn()
                                        if self._epoch_fn else None),
                   "hedge_floor_ms": round(self._hedge_floor_s * 1e3, 3),
                   "deadline_ms": round(self._deadline_s * 1e3, 3),
                   # the live-index view: generations this router has
                   # seen workers answer from (each worker's own
                   # index_generation rides in its replica entry)
                   "generations_seen": gens,
                   "shards": shards}
        if self.cache is not None:
            from .result_cache import cache_counters

            payload["cache"] = {**self.cache.snapshot(),
                                **cache_counters()}
        with self._health_lock:
            self._health_cache = (time.monotonic(), payload)
        return payload

    def _poll_worker_health(self, addr: str | None) -> dict:
        if not addr:
            return {"up": False, "error": "no address"}
        host, port = addr.rsplit(":", 1)
        try:
            conn = http.client.HTTPConnection(host, int(port),
                                              timeout=self._connect_s)
            try:
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                body = json.loads(resp.read())
            finally:
                conn.close()
        except Exception as e:  # noqa: BLE001 — down IS the answer
            return {"up": False, "error": repr(e)}
        return {"up": True,
                "worker": body.get("worker"),
                "ladder": body.get("ladder"),
                "breaker_worker": body.get("breaker"),
                "queue_depth": body.get("queue_depth")}

    def stats(self) -> dict:
        reg = get_registry()
        return {name: reg.get(name)
                for name in reg.counter_names()
                if name.startswith("router.")}

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._pool.shutdown(wait=True, cancel_futures=True)

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
