"""The overload-resilient serving frontend: admission control, a
degradation ladder, and a circuit breaker wrapped around one Scorer.

PR 1 gave a single request a bounded-latency story (per-batch deadline →
host fallback, tagged degraded). This module is the story for a POPULATION
of requests — the overload axis:

    request ──► admission control ──► degradation ladder ──► breaker
                (bounded queue,        (what work this         (device or
                 shed past it)          level still does)       host path)

- **Admission** (admission.py): `max_concurrency` running, `max_queue`
  waiting, everything else shed instantly with a structured `Overloaded`.
- **Ladder**: under queue pressure or repeated dispatch failures the
  frontend steps down through explicit service levels — full →
  no_rerank (drop the rerank + snippet stages) → hot_only (score only
  the tiered hot strip; skipped on the dense layout, which has no
  cheaper stage) → shed (admission rejects everything). Each response is
  tagged with the level that produced it (SearchResult.level). Stepping
  UP requires `recover_successes` consecutive calm observations
  (hysteresis — one good request must not flap the ladder).
- **Breaker** (breaker.py): N consecutive device failures open it; open
  means requests go straight to the host-CPU fallback with no device
  dispatch and NO deadline wait (the ≥10× latency save when the device
  is plain gone), with half-open probes to detect recovery.
- **Coalescer** (batching.py, ISSUE 9; opt-in via ServingConfig
  .coalesce): between admission and dispatch, concurrent compatible
  requests are packed into ONE padded kernel call from a precompiled
  batch-size ladder and demuxed — amortizing the fixed per-dispatch
  round trip that each caller otherwise pays alone. Per-request
  semantics (level, breaker verdict, explain depth, deadline
  accounting) stay tagged per slot.

Everything here is thread-safe: the intended caller is one frontend
shared by many request threads. Correctness under concurrency rides on
Scorer's per-request tagged dispatch (topk_tagged); the racy
`degraded_last` alias is gone.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass

from .. import obs
from ..obs import disttrace
from ..obs import trace as obs_trace
from ..search.scorer import Scorer, SearchResult
from ..utils.report import RecoveryCounters, serving_counters
from .admission import AdmissionController, Overloaded
from .breaker import CircuitBreaker

logger = logging.getLogger(__name__)

# the full ladder, cheapest-first never — order is strictly decreasing
# work per request; "shed" must stay last (admission consults it)
LEVEL_FULL = "full"
LEVEL_NO_RERANK = "no_rerank"
LEVEL_HOT_ONLY = "hot_only"
LEVEL_SHED = "shed"


@dataclass(frozen=True)
class ServingConfig:
    """Tuning knobs (RUNBOOK "Serving under overload" documents how to
    pick them). Defaults suit a small box driving CI-scale traffic."""

    max_concurrency: int = 4       # requests executing at once
    max_queue: int = 16            # requests allowed to WAIT for a slot
    deadline_s: float | None = None   # per-request device dispatch bound
    queue_timeout_s: float | None = None  # max slot wait (None: deadline_s)
    breaker_threshold: int = 5     # consecutive device failures to open
    breaker_cooldown_s: float = 1.0   # open time before a half-open probe
    step_down_pressure: float = 0.75  # queue occupancy that steps down
    step_up_pressure: float = 0.25    # calm threshold for recovery credit
    fail_threshold: int = 3        # consecutive failures that step down
    recover_successes: int = 16    # calm observations to step up one level
    down_cooldown_s: float = 0.05  # min time between two down-steps
    # continuous micro-batching (ISSUE 9; batching.py). None defaults
    # defer to the TPU_IR_BATCH_* env knobs at frontend construction.
    coalesce: bool = False            # coalesce concurrent queries
    coalesce_wait_ms: float | None = None  # promoted-leader linger bound
    batch_ladder: tuple | None = None      # compiled batch-size rungs
    batch_width: int | None = None         # pinned analyzed query width
    precompile: bool = True           # walk the ladder at start
    precompile_ks: tuple = (10,)      # k depths the ladder walk warms
    # generation-keyed exact-hit result cache (ISSUE 15;
    # result_cache.py), consulted ahead of admission and the coalescer.
    # None defers to TPU_IR_CACHE_RESULTS; 0 disables.
    cache_entries: int | None = None


class DegradationLadder:
    """Thread-safe service-level state machine with hysteresis.

    Down-transitions are fast (pressure at/above `step_down_pressure`,
    or `fail_threshold` consecutive dispatch failures) but rate-limited
    to one per `down_cooldown_s`: overload must be answered now, yet one
    burst arriving in the same millisecond must not teleport the ladder
    from full to shed before the cheaper levels got a chance to absorb
    it. Up-transitions need `recover_successes` consecutive observations
    in the calm zone (pressure at/below `step_up_pressure`, no failures)
    and move ONE level at a time — recovery is earned, so the ladder
    cannot flap."""

    def __init__(self, levels: tuple, cfg: ServingConfig, on_transition,
                 clock=time.monotonic):
        self._levels = tuple(levels)
        self._cfg = cfg
        self._on_transition = on_transition  # (direction, from, to)
        self._clock = clock
        self._lock = threading.Lock()
        self._idx = 0
        self._fails = 0
        self._successes = 0
        self._last_down = -float("inf")

    @property
    def levels(self) -> tuple:
        return self._levels

    def level(self) -> str:
        with self._lock:
            return self._levels[self._idx]

    def observe(self, *, pressure: float, failed: bool) -> None:
        """Feed one completed (or shed) request's signals: the queue
        pressure seen around it, and whether its device dispatch failed
        (deadline expiry / device loss — sheds and breaker-open host
        serves are NOT dispatch failures)."""
        cfg = self._cfg
        moved = None
        with self._lock:
            if failed:
                self._fails += 1
                self._successes = 0
            else:
                self._fails = 0
            if (pressure >= cfg.step_down_pressure
                    or self._fails >= cfg.fail_threshold):
                self._successes = 0
                now = self._clock()
                if (self._idx + 1 < len(self._levels)
                        and now - self._last_down >= cfg.down_cooldown_s):
                    moved = ("down", self._levels[self._idx],
                             self._levels[self._idx + 1])
                    self._idx += 1
                    self._fails = 0
                    self._last_down = now
            elif not failed and pressure <= cfg.step_up_pressure:
                self._successes += 1
                if (self._successes >= cfg.recover_successes
                        and self._idx > 0):
                    moved = ("up", self._levels[self._idx],
                             self._levels[self._idx - 1])
                    self._idx -= 1
                    self._successes = 0
        if moved is not None:
            self._on_transition(*moved)

    def snapshot(self) -> dict:
        with self._lock:
            return {"level": self._levels[self._idx],
                    "consecutive_failures": self._fails,
                    "recovery_credit": self._successes}


class ServingFrontend:
    """Thread-safe serving wrapper around one loaded Scorer (any layout:
    dense, tiered sparse, or sharded). Callers' threads run their own
    requests — the frontend owns no worker pool, so there is nothing to
    shut down and nothing to leak; concurrency is bounded by admission,
    not by thread ownership."""

    def __init__(self, scorer: Scorer, config: ServingConfig | None = None):
        self.config = cfg = config or ServingConfig()
        self.admission = AdmissionController(cfg.max_concurrency,
                                             cfg.max_queue)
        self.breaker = CircuitBreaker(cfg.breaker_threshold,
                                      cfg.breaker_cooldown_s)
        # dense layouts have no hot tier — no cheaper device stage exists,
        # so the ladder goes straight from no_rerank to shed
        levels = ((LEVEL_FULL, LEVEL_NO_RERANK, LEVEL_HOT_ONLY, LEVEL_SHED)
                  if scorer.layout in ("sparse", "sharded")
                  else (LEVEL_FULL, LEVEL_NO_RERANK, LEVEL_SHED))
        self.ladder = DegradationLadder(levels, cfg, self._on_transition)
        # (scorer, batcher) ride ONE tuple published by a single
        # reference assignment: the request path reads the pair once at
        # entry, so a generation swap (reload_generation) can never
        # tear a request across two scorers — or hand it a batcher
        # whose internal scorer is not the one it captured
        self._serving = (scorer, self._make_batcher(scorer))
        # the single-process exact-hit result cache (ISSUE 15): keyed
        # on analyzed term ids + every route-selecting flag + the
        # serving generation; consulted BEFORE admission (a hit costs
        # no slot) and ahead of the coalescer
        from .result_cache import ResultCache, resolve_capacity

        cap = resolve_capacity(cfg.cache_entries)
        self.cache = (ResultCache(cap, name="frontend")
                      if cap > 0 else None)
        if self.cache is not None:
            self.cache.bump_generation(scorer.generation)
        self._counters = RecoveryCounters()
        # the embedded metrics server's /healthz reports this frontend's
        # breaker/ladder/queue state for as long as it is alive (weakref
        # — registering must not extend the scorer's lifetime)
        from ..obs.server import register_health_source

        register_health_source(self)

    def _make_batcher(self, scorer: Scorer):
        """The coalescing scheduler (ISSUE 9) for one scorer: packs
        concurrent compatible requests into one padded dispatch;
        precompiling the rung ladder here means no serving caller ever
        eats an XLA compile — on construction AND on every generation
        swap (the first post-swap request is the worst moment to
        compile)."""
        cfg = self.config
        if not cfg.coalesce:
            return None
        from .batching import CoalescingScheduler

        batcher = CoalescingScheduler(
            scorer, deadline_s=cfg.deadline_s,
            wait_ms=cfg.coalesce_wait_ms, ladder=cfg.batch_ladder,
            width=cfg.batch_width)
        if cfg.precompile:
            batcher.precompile(ks=cfg.precompile_ks)
        return batcher

    @property
    def scorer(self) -> Scorer:
        return self._serving[0]

    @property
    def batcher(self):
        return self._serving[1]

    def reload_generation(self, scorer: Scorer | None = None, *,
                          generation: int | None = None) -> Scorer:
        """Swap serving to a new index generation with ZERO downtime:
        load (or accept) the new generation's scorer, build + warm its
        coalescer, then publish both as one reference assignment.
        In-flight requests finish untouched on the scorer they captured
        at entry (its arrays stay alive exactly as long as they hold
        them); every request entering after the publish serves the new
        generation and is tagged with it. Nothing here blocks the
        request path — the expensive work (mmap load, precompile) runs
        before the publish, outside any lock."""
        import time as _time

        from .. import obs

        t0 = _time.perf_counter()
        if scorer is None:
            scorer = self.scorer.reload_generation(generation)
        batcher = self._make_batcher(scorer)
        self._serving = (scorer, batcher)   # THE publish
        if self.cache is not None:
            # invalidation is by KEY (the generation is in it); the
            # bump purges the now-unreachable old-generation entries so
            # the bounded capacity serves the new corpus, and counts
            # them as cache.stale_generation
            self.cache.bump_generation(scorer.generation)
        self._count("generation_swap")
        reg = obs.get_registry()
        reg.set_gauge("generation.current", scorer.generation)
        reg.observe("generation.swap", _time.perf_counter() - t0)
        logger.info("serving swapped to generation %s", scorer.generation)
        return scorer

    # -- accounting --------------------------------------------------------

    def _count(self, name: str, amount: int = 1) -> None:
        """Every event lands in BOTH ledgers: this frontend's own counters
        (the soak harness asserts shed + served == submitted per
        instance) and the process-wide serving_counters() that
        `tpu-ir stats` scrapes."""
        self._counters.incr(name, amount)
        serving_counters().incr(name, amount)

    def _on_transition(self, direction: str, frm: str, to: str) -> None:
        self._count(f"level_step_{direction}")
        logger.warning("degradation ladder stepped %s: %s -> %s",
                       direction, frm, to)

    @staticmethod
    def _observe_latency(name: str, t0: float) -> None:
        """Record one end-to-end request latency. Gated on the tracing
        flag so TPU_IR_TRACE=0 disables EVERY latency histogram, not
        just the span-derived ones (the documented contract: counters
        stay on, latency instrumentation goes dark)."""
        if obs.enabled():
            obs.get_registry().observe(name, time.perf_counter() - t0)

    def stats(self) -> dict:
        """This frontend's counters + control-plane state, one dict."""
        scorer, batcher = self._serving
        out = dict(self._counters.snapshot())
        out["ladder"] = self.ladder.snapshot()
        out["breaker"] = self.breaker.snapshot()
        out["queue_depth"] = self.admission.queue_depth()
        out["in_flight"] = self.admission.in_flight()
        out["generation"] = scorer.generation
        if batcher is not None:
            out["batching"] = batcher.snapshot()
        if self.cache is not None:
            from .result_cache import cache_counters

            out["cache"] = {**self.cache.snapshot(), **cache_counters()}
        return out

    # -- the request path --------------------------------------------------

    def search(self, text: str, *, k: int = 10, scoring: str = "tfidf",
               rerank: int | None = None,
               snippets: bool = False,
               explain_k: int = 0,
               return_docids: bool = True) -> SearchResult:
        """Serve one query. Returns a SearchResult tagged with the
        service level (`level`) and fallback flag (`degraded`) that
        produced it, or raises Overloaded (a structured shed — the
        request was NOT executed). `rerank`/`snippets`/`explain_k` are
        what the caller WANTS; the ladder decides what it gets —
        explain_k rides per-slot even inside a coalesced batch.

        Telemetry: the whole call is one "request" span tree (ladder →
        admission_wait → breaker → dispatch/kernel → fallback) and its
        end-to-end latency lands in the `request.<level>` histogram —
        sheds included (`request.shed` is the time-to-reject, the
        number that proves shedding is cheap)."""
        t0 = time.perf_counter()
        self._count("submitted")
        # ONE read of the (scorer, batcher) pair for the whole request:
        # a concurrent generation swap republishes the tuple, and this
        # request must finish entirely on the pair it entered with
        scorer, batcher = self._serving
        # distributed trace context: adopt the router's (installed by the
        # worker RPC handler) when present, mint fresh when this frontend
        # IS the admission edge (unrouted / direct API callers)
        ctx = disttrace.current()
        minted = ctx is None and disttrace.enabled()
        if minted:
            ctx = disttrace.mint()
        with disttrace.use(ctx if minted else None), \
                obs_trace("request", scoring=scoring) as root:
            with obs_trace("ladder") as lsp:
                level = self.ladder.level()
                lsp.set("level", level)
            root.set("level", level)
            if level == LEVEL_SHED:
                self._count("shed_level")
                pressure = self.admission.pressure()
                # sheds are instant, so pressure falls while shedding:
                # these observations are how the ladder earns its way
                # back up
                self.ladder.observe(pressure=pressure, failed=False)
                self._observe_latency("request.shed", t0)
                root.set("shed", True)
                if minted:
                    disttrace.slo_record(
                        "shed", (time.perf_counter() - t0) * 1e3,
                        ok=False, classification="shed")
                raise Overloaded("shed_level",
                                 queue_depth=self.admission.queue_depth(),
                                 level=level)
            # exact-hit result cache (ISSUE 15), ahead of admission AND
            # the coalescer: a hit costs the lookup alone — no slot, no
            # breaker consult, no dispatch — and replays a stored
            # full-route response bit-identically
            cache_key = self._cache_key(scorer, text, k=k,
                                        scoring=scoring, rerank=rerank,
                                        level=level, snippets=snippets,
                                        explain_k=explain_k,
                                        return_docids=return_docids)
            if cache_key is not None:
                t_lookup = time.perf_counter()
                hit = self.cache.get(cache_key)
                self._observe_latency("cache.lookup", t_lookup)
                if hit is not None:
                    res = SearchResult(hit)
                    res.level = level
                    res.generation = scorer.generation
                    res.trace_id = ctx.trace_id if ctx is not None else None
                    root.set("cached", True)
                    self._count("served_cache")
                    self._observe_latency(f"request.{level}", t0)
                    if minted:
                        disttrace.slo_record(
                            level, (time.perf_counter() - t0) * 1e3,
                            classification="full")
                    return res
            timeout = (self.config.queue_timeout_s
                       if self.config.queue_timeout_s is not None
                       else self.config.deadline_s)
            try:
                # the admit context is entered by hand so the
                # admission_wait span measures ONLY the slot wait (the
                # queue_full/queue_timeout sheds raise through it and
                # ride the span as its recorded error), not the serve
                admit_cm = self.admission.admit(queue_timeout_s=timeout)
                with obs_trace("admission_wait"):
                    admit_cm.__enter__()
                try:
                    res = self._serve(text, k=k, scoring=scoring,
                                      rerank=rerank, snippets=snippets,
                                      level=level, explain_k=explain_k,
                                      return_docids=return_docids,
                                      scorer=scorer, batcher=batcher)
                finally:
                    admit_cm.__exit__(None, None, None)
                if (cache_key is not None and not res.degraded
                        and not res.partial):
                    # only clean outcomes are frozen: a degraded
                    # response is transient serving weather, and the
                    # key's level flags already guarantee this entry
                    # can only answer requests the ladder would route
                    # identically
                    self.cache.put(cache_key, tuple(res),
                                   generation=res.generation)
                res.trace_id = ctx.trace_id if ctx is not None else None
                # degraded/partial flags on the root make the trace
                # tail-kept (the interesting traces survive sampling)
                root.set("degraded", bool(res.degraded))
                if getattr(res, "partial", False):
                    root.set("partial", True)
                self._observe_latency(f"request.{level}", t0)
                if minted:
                    cls = ("degraded" if res.degraded
                           else "partial" if getattr(res, "partial", False)
                           else "full")
                    disttrace.slo_record(
                        res.level, (time.perf_counter() - t0) * 1e3,
                        classification=cls)
                return res
            except Overloaded as e:
                # only admission sheds reach here (queue_full /
                # queue_timeout)
                self._count(f"shed_{e.reason}")
                # a full queue is the strongest pressure signal there is
                self.ladder.observe(pressure=1.0, failed=False)
                self._observe_latency("request.shed", t0)
                root.set("shed", True)
                if minted:
                    disttrace.slo_record(
                        "shed", (time.perf_counter() - t0) * 1e3,
                        ok=False, classification="shed")
                raise

    def _cache_key(self, scorer: Scorer, text: str, *, k: int,
                   scoring: str, rerank: int | None, level: str,
                   snippets: bool, explain_k: int,
                   return_docids: bool) -> tuple | None:
        """The exact-hit cache key for one request, or None when the
        request is not cacheable (cache off; phrase/glob/fuzzy text —
        operator expansion must not collide with literal terms;
        explain/snippet requests — they attach per-request artifacts;
        raw-docno requests — the worker RPC surface rides the ROUTER
        cache above it instead).

        Normalized terms are the analyzed term-id SEQUENCE (order and
        multiplicity preserved: float accumulation follows slot order,
        so reordering terms may change result bits — the key must not
        merge such requests). Every flag that selects the traced
        program or the serving route is in the key, plus the captured
        scorer's generation — a swap moves the key space, never the
        entries."""
        from .result_cache import cacheable_text

        if (self.cache is None or snippets or explain_k
                or not return_docids or not cacheable_text(text)):
            return None
        row = scorer.analyze_queries([text])[0]
        terms = tuple(int(t) for t in row if t >= 0)
        use_rerank = rerank if level == LEVEL_FULL else None
        return (terms, int(k), scoring, use_rerank,
                level == LEVEL_HOT_ONLY, int(scorer.generation))

    def _serve(self, text: str, *, k: int, scoring: str,
               rerank: int | None, snippets: bool,
               level: str, explain_k: int = 0,
               return_docids: bool = True,
               scorer: Scorer | None = None,
               batcher=None) -> SearchResult:
        if scorer is None:  # direct callers (tests) without the capture
            scorer, batcher = self._serving
        with obs_trace("breaker") as bsp:
            allowed, is_probe = self.breaker.allow_device()
            bsp.set("allowed", allowed)
            bsp.set("probe", is_probe)
        force_host = not allowed
        if is_probe:
            self._count("breaker_probes")
        use_rerank = rerank if level == LEVEL_FULL else None
        try:
            if (batcher is not None and '"' not in text
                    and return_docids):
                # the coalesced path: this thread's request may ride a
                # batch-mate's kernel call — its level/wait/occupancy
                # are tagged per SLOT by the scheduler (the leader's
                # thread-local context would be wrong for followers);
                # phrase queries score on the host and go solo below,
                # as do raw-docid requests (the shard-worker RPC
                # surface): BatchKey doesn't carry the result-key flavor
                res = batcher.submit(
                    text, k=k, scoring=scoring, rerank=use_rerank,
                    hot_only=(level == LEVEL_HOT_ONLY),
                    force_host=force_host, level=level,
                    queue_depth=self.admission.queue_depth(),
                    explain_k=explain_k)
            else:
                # the query log records inside the scorer, which only
                # knows flags; the context stamps each entry with the
                # ladder's true service level + the queue depth it was
                # served under
                with obs.querylog.request_context(
                        level=level,
                        queue_depth=self.admission.queue_depth()):
                    res = scorer.search_batch(
                        [text], k=k, scoring=scoring, rerank=use_rerank,
                        deadline_s=self.config.deadline_s,
                        force_host=force_host,
                        hot_only=(level == LEVEL_HOT_ONLY),
                        explain_k=explain_k,
                        return_docids=return_docids)[0]
        except BaseException:
            # not a device verdict (bad query, program bug): release any
            # probe slot this request held so the breaker cannot wedge
            # half-open forever, and let the error surface structurally
            if not force_host:
                self.breaker.abort(is_probe=is_probe)
            raise
        res.level = level
        # attribution across rolling upgrades: the response names the
        # exact corpus snapshot that answered it
        res.generation = scorer.generation
        dispatch_failed = False
        # under coalescing, one shared dispatch serves many slots; only
        # the batch's voting slot feeds the breaker (its threshold
        # counts consecutive DISPATCH failures — N slots echoing one
        # failed dispatch would trip it from a single event). Probe
        # slots always vote: the half-open slot must be released by its
        # own verdict. Solo/non-coalesced results vote by default.
        votes = getattr(res, "breaker_vote", True) or is_probe
        if force_host:
            self._count("served_breaker_host")
        else:
            # res.degraded is THIS request's tagged outcome: a device
            # dispatch that expired its deadline or lost the device
            dispatch_failed = res.degraded
            if dispatch_failed:
                if votes and self.breaker.record_failure(
                        is_probe=is_probe):
                    self._count("breaker_opened")
                    # an opening breaker is an incident boundary: freeze
                    # the recent traces + telemetry (rate-limited — a
                    # flapping breaker under chaos cannot fill a disk)
                    obs.flight_dump("breaker_open", extra={
                        "breaker": self.breaker.snapshot(),
                        "ladder": self.ladder.snapshot()})
            elif votes:
                self.breaker.record_success(is_probe=is_probe)
        if res.degraded:
            self._count("degraded")
        self._count(f"served_{level}")
        if snippets and level == LEVEL_FULL and not res.degraded:
            res.snippets = [scorer.snippet(text, key) for key, _ in res]
        self.ladder.observe(pressure=self.admission.pressure(),
                            failed=dispatch_failed)
        return res
