"""Overload-resilient serving frontend (admission control, degradation
ladder, circuit breaker) for a loaded Scorer — see frontend.py for the
architecture and RUNBOOK "Serving under overload" for operations."""

from .admission import AdmissionController, Overloaded
from .autoscale import Autoscaler, AutoscaleConfig, autoscale_enabled
from .batching import BatchKey, CoalescingScheduler, batch_ladder
from .breaker import CLOSED, HALF_OPEN, OPEN, CircuitBreaker
from .frontend import (
    LEVEL_FULL,
    LEVEL_HOT_ONLY,
    LEVEL_NO_RERANK,
    LEVEL_SHED,
    DegradationLadder,
    ServingConfig,
    ServingFrontend,
)
from .generation import rolling_swap, swap_microbench
from .residency import prewarm_hot_residency, residency_hint
from .result_cache import ResultCache, cache_counters, live_caches
from .workload import Workload, resolve_workload
from .router import (
    Router,
    RouterConfig,
    merge_candidate_scores,
    merge_shard_topk,
)
from .shardset import ShardSet, serve_worker, worker_rpc_handlers
from .soak import (
    DEFAULT_CHAOS_PLAN,
    make_queries,
    run_concurrency_sweep,
    run_distributed_soak,
    run_soak,
)

__all__ = [
    "AdmissionController", "Overloaded",
    "CircuitBreaker", "CLOSED", "OPEN", "HALF_OPEN",
    "ServingFrontend", "ServingConfig", "DegradationLadder",
    "CoalescingScheduler", "BatchKey", "batch_ladder",
    "LEVEL_FULL", "LEVEL_NO_RERANK", "LEVEL_HOT_ONLY", "LEVEL_SHED",
    "Router", "RouterConfig", "ShardSet", "serve_worker",
    "worker_rpc_handlers", "merge_shard_topk", "merge_candidate_scores",
    "run_soak", "make_queries", "run_concurrency_sweep",
    "run_distributed_soak", "DEFAULT_CHAOS_PLAN",
    "rolling_swap", "swap_microbench",
    "Autoscaler", "AutoscaleConfig", "autoscale_enabled",
    "Workload", "resolve_workload",
    "ResultCache", "cache_counters", "live_caches",
    "prewarm_hot_residency", "residency_hint",
]
