"""Shard workers for the scatter-gather serving tier (ISSUE 10).

One logical index, N doc shards, R replicas per shard: every WORKER is a
full serving process — PR-1/2 Scorer + ServingFrontend (admission,
degradation ladder, circuit breaker, deadline fallback) — restricted to
its shard's doc range (Scorer.load(doc_range=...): the layout keeps full
geometry, out-of-range postings are tf-zeroed, so in-range docs score
BIT-identically to the single-process scorer — the router's exact-merge
contract). The worker's serving surface is the PR-4 observability server
(obs/server.py) grown an RPC route:

  POST /rpc/search     {"text", "k", "scoring"}        -> local top-k
                       (raw docids + scores; level/degraded tagged)
  POST /rpc/cosine_at  {"text", "cand": [docids]}      -> stage-2 cosine
                       scores at the router's merged candidates
  GET  /healthz        the PR-4 payload + worker identity (shard id,
                       replica, doc range, spawn generation) — the
                       router's failover/aggregation signal

Two deployment forms share all of this code:

- **in-process workers** (`serve_worker()`): scorer + frontend + server
  in the calling process — the form the router unit tests and property
  suite drive (no subprocess cost, full HTTP path);
- **subprocess workers** (`ShardSet`): one `python -m
  tpu_ir.serving.shardset <config.json>` process per (shard, replica),
  ready-file handshake, SIGKILL-able for chaos, respawnable with a
  bumped generation. A worker watches its stdin pipe and exits when the
  parent dies — no orphan serving processes.

The reference's only distribution was HDFS reads under one JVM
(PAPER.md §0); this is the "millions of users" fan-out topology ROADMAP
item 4 names, built from the fault machinery PRs 1-9 already proved.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time

logger = logging.getLogger(__name__)

READY_POLL_S = 0.05


def rpc_post(addr: str, path: str, payload: dict,
             timeout_s: float, headers: dict | None = None) -> dict:
    """One worker HTTP RPC attempt — THE client-side framing of the
    /rpc contract (router fan-out, rolling swaps, tests), defined once
    next to the server side so the two cannot drift. Raises on any
    failure (refused, reset, timeout, non-200); the caller decides
    what a failure means (breaker verdict, skip-and-respawn, ...).
    The socket timeout bounds connect AND read. `headers` merges extra
    request headers in — the router's `traceparent` propagation
    (ISSUE 18) rides here, invisible to the JSON payload contract."""
    import http.client
    import json as _json

    host, port = addr.rsplit(":", 1)
    conn = http.client.HTTPConnection(host, int(port),
                                      timeout=max(timeout_s, 1e-3))
    hdrs = {"Content-Type": "application/json"}
    if headers:
        hdrs.update(headers)
    try:
        conn.request("POST", f"/rpc/{path}",
                     body=_json.dumps(payload),
                     headers=hdrs)
        resp = conn.getresponse()
        body = resp.read()
        if resp.status != 200:
            raise RuntimeError(
                f"worker {addr} /rpc/{path} -> {resp.status}: "
                f"{body[:200]!r}")
        return _json.loads(body)
    finally:
        conn.close()


def get_worker_health(addr: str, timeout_s: float) -> dict:
    """GET one worker's /healthz payload (the identity/generation view
    serve_worker's extra_health merges in)."""
    import http.client
    import json as _json

    host, port = addr.rsplit(":", 1)
    conn = http.client.HTTPConnection(host, int(port),
                                      timeout=max(timeout_s, 1e-3))
    try:
        conn.request("GET", "/healthz")
        return _json.loads(conn.getresponse().read())
    finally:
        conn.close()


def worker_rpc_handlers(frontend, scorer=None, *, reload_fn=None) -> dict:
    """The worker's RPC surface over one (doc-range-restricted) scorer.
    Handlers run on the HTTP server's request threads; concurrency is
    bounded by the frontend's admission control, errors surface as the
    server's 503 (Overloaded) / 500 (anything else) contract.

    The scorer is read through the frontend PER CALL (not captured):
    a generation swap republishes frontend.scorer, and the very next
    RPC must serve — and tag — the new generation. Every response
    carries `generation` so the router can refuse to merge hits from
    two different corpus snapshots (the mixed-generation window).
    `reload_fn(generation|None)` (live-index workers only) serves
    POST /rpc/reload — the rolling-upgrade handoff."""
    del scorer  # back-compat positional slot; frontend.scorer is live

    def search(payload: dict) -> dict:
        res = frontend.search(
            str(payload["text"]),
            k=int(payload.get("k", 10)),
            scoring=str(payload.get("scoring", "tfidf")),
            rerank=None,
            return_docids=False)
        return {
            "hits": [[int(d), float(s)] for d, s in res],
            "level": res.level,
            "degraded": bool(res.degraded),
            "generation": int(res.generation),
        }

    def cosine_at(payload: dict) -> dict:
        sc = frontend.scorer
        scores = sc.cosine_scores_at(
            [str(payload["text"])],
            [int(c) for c in payload.get("cand", [])])
        return {"scores": [float(s) for s in scores[0]],
                "generation": int(sc.generation)}

    handlers = {"search": search, "cosine_at": cosine_at}
    if reload_fn is not None:
        def reload(payload: dict) -> dict:
            gen = payload.get("generation")
            return reload_fn(None if gen is None else int(gen))

        handlers["reload"] = reload
    return handlers


def serve_worker(index_dir: str, shard: int, num_shards: int, *,
                 layout: str = "sparse", port: int = 0,
                 host: str = "127.0.0.1",
                 replica: int = 0, generation: int = 0,
                 index_generation: int | None = None,
                 deadline_s: float | None = None,
                 max_concurrency: int = 4, max_queue: int = 16,
                 warm: bool = True):
    """Load a shard-restricted scorer, wrap it in a ServingFrontend, and
    serve it over an RPC-enabled obs server. Returns (server, frontend,
    scorer) — the caller owns `server.stop()`. This is the whole worker;
    the subprocess main below is just config plumbing around it.

    `index_dir` may be a LIVE index dir (index/segments.py): the worker
    then serves its (`index_generation` or current-servable) generation
    and exposes POST /rpc/reload — load the named (default: latest
    servable) generation with a freshly computed doc_range, WARM it,
    and swap with zero downtime (the old generation keeps serving until
    the publish). `generation` is the SPAWN generation (process
    lifetime, bumped by ShardSet.respawn); the index generation is a
    separate axis and both ride /healthz."""
    from ..index import segments as seg
    from ..search.scorer import Scorer
    from ..obs.server import MetricsServer
    from .frontend import ServingConfig, ServingFrontend

    live = seg.is_live(index_dir)

    def load_for(gen: int | None) -> "Scorer":
        from ..index import format as fmt
        from ..search.layout import shard_doc_ranges

        resolved, g = seg.resolve_serving(index_dir, gen)
        meta = fmt.IndexMetadata.load(resolved)
        # the doc partition follows num_docs: each generation re-deals
        # the (possibly grown) corpus over the SAME shard grid
        rg = shard_doc_ranges(meta.num_docs, num_shards)[shard]
        return Scorer.load_generation(
            index_dir, g, layout=layout, deadline_s=deadline_s,
            doc_range=rg)

    scorer = load_for(index_generation)
    # distributed-trace service identity: every span this process emits
    # is attributed to this (shard, replica) in the stitched waterfall
    from ..obs import disttrace
    disttrace.set_service(f"worker-s{shard}r{replica}")
    frontend = ServingFrontend(scorer, ServingConfig(
        max_concurrency=max_concurrency, max_queue=max_queue,
        deadline_s=deadline_s))
    # the hot-postings residency hint (ISSUE 15, serving/residency.py):
    # fed by the doctor's df-skew report over THIS shard's df column —
    # on a Zipf-shaped corpus the block-max strips / tf matrix go
    # device-resident at load, before the ready file is written
    residency = {"engaged": False}

    def info() -> dict:
        from ..obs import get_registry

        sc = frontend.scorer
        reg = get_registry()
        return {"worker": {
            "shard": shard, "replica": replica, "num_shards": num_shards,
            "doc_range": list(sc.doc_range or ()),
            "generation": generation,
            "index_generation": sc.generation,
            "live": live,
            "residency": residency,
            # the drain handshake's signal (ISSUE 16): a retiring
            # worker is terminated only once executing + queued read 0
            "in_flight": frontend.admission.in_flight(),
            "queued": frontend.admission.queue_depth(),
            # THIS process's compile counters: the warm-start pin reads
            # them across a scale-up (delta must be 0 — the precompile
            # walk ran before the replica entered the dispatch grid)
            "compiles": {"count": reg.get("compile.count"),
                         "recompiles": reg.get("compile.recompiles")},
            "pid": os.getpid(), "layout": sc.layout,
        }}

    reload_fn = None
    if live:
        def reload_fn(gen: int | None) -> dict:
            from .residency import prewarm_hot_residency

            new = load_for(gen)
            if warm:
                # warm BEFORE the publish: the first post-swap request
                # must not eat an XLA compile inside a shard deadline
                _warm_worker(new)
                residency.clear()
                residency.update(prewarm_hot_residency(new))
            frontend.reload_generation(new)
            return {"generation": new.generation,
                    "num_docs": new.meta.num_docs,
                    "doc_range": list(new.doc_range or ())}

    server = MetricsServer(
        port=port, host=host,
        rpc_handlers=worker_rpc_handlers(frontend, reload_fn=reload_fn),
        extra_health=info).start()
    if warm:
        from .residency import prewarm_hot_residency

        _warm_worker(scorer)
        residency.update(prewarm_hot_residency(scorer))
    return server, frontend, scorer


def shard_doc_ranges_for(index_dir: str, shard: int,
                         num_shards: int) -> tuple:
    """This shard's (lo, hi) docid range from the index metadata — the
    partition every worker and the router derive identically. A live
    dir resolves to its current servable generation first."""
    from ..index import format as fmt
    from ..index import segments as seg
    from ..search.layout import shard_doc_ranges

    resolved, _ = seg.resolve_serving(index_dir)
    meta = fmt.IndexMetadata.load(resolved)
    return shard_doc_ranges(meta.num_docs, num_shards)[shard]


def _warm_worker(scorer, ks=(10,), rerank_ks=(25,)) -> None:
    """Warm the compile shapes real traffic mints (1-3 term queries ->
    pow2 widths 1/2/4; k is STATIC, so each serving depth in `ks` and
    each rerank candidate count in `rerank_ks` is its own program) for
    both scoring models, so the first routed request never eats an XLA
    compile inside its shard deadline. The persistent compilation cache
    (Scorer.load enables it) makes this near-free for every worker
    after the first."""
    import numpy as np

    all_terms = list(scorer.vocab.terms)
    if not all_terms:
        return
    # the MaxScore schedule compiles DIFFERENT programs for hot-free vs
    # hot-bearing query blocks — warm both: prefix texts over cold
    # terms, plus the same widths seeded with a hot-strip term when the
    # layout has one
    def prefixes(ts):
        return [" ".join(ts[:n]) for n in range(1, len(ts) + 1)]

    texts = prefixes(all_terms[:3])
    if scorer.layout == "sparse":
        hot_ids = np.nonzero(scorer._hot_rank_host() >= 0)[0]
        if len(hot_ids):
            texts += prefixes([scorer.vocab.term(int(hot_ids[0]))]
                              + all_terms[:2])
    for scoring in ("tfidf", "bm25"):
        for txt in texts:
            for k in ks:
                scorer.search_batch([txt], k=int(k),
                                    scoring=scoring, return_docids=False)
    for c in rerank_ks:
        # the two-phase rerank's shapes: stage-1 BM25 top-C plus the
        # [1, C] cosine_at gather the router's phase 2 dispatches
        for txt in texts:
            scorer.search_batch([txt], k=int(c), scoring="bm25",
                                return_docids=False)
            scorer.cosine_scores_at([txt], [0] * int(c))


# -- subprocess worker ------------------------------------------------------


def _watch_parent() -> None:
    """Exit when the parent closes our stdin pipe (it died or stopped
    us): a SIGKILLed router must never leave orphan workers serving."""

    def run():
        try:
            while sys.stdin.buffer.read(1):
                pass
        except Exception:  # noqa: BLE001 — any read failure means gone
            pass
        os._exit(0)

    threading.Thread(target=run, name="tpu-ir-worker-parent-watch",
                     daemon=True).start()


def worker_main(config_path: str) -> int:
    """`python -m tpu_ir.serving.shardset <config.json>`: the subprocess
    entry. Serves until SIGTERM / parent death; writes the ready file
    (port + pid, atomic rename) only after the warm-up, so a parent that
    saw the file can fan out immediately."""
    with open(config_path, encoding="utf-8") as f:
        cfg = json.load(f)
    _watch_parent()
    index_generation = cfg.get("index_generation")
    server, _frontend, _scorer = serve_worker(
        cfg["index_dir"], int(cfg["shard"]), int(cfg["num_shards"]),
        layout=cfg.get("layout", "sparse"), port=int(cfg.get("port", 0)),
        replica=int(cfg.get("replica", 0)),
        generation=int(cfg.get("generation", 0)),
        index_generation=(None if index_generation is None
                          else int(index_generation)),
        deadline_s=cfg.get("deadline_s"),
        max_concurrency=int(cfg.get("max_concurrency", 4)),
        max_queue=int(cfg.get("max_queue", 16)),
        warm=bool(cfg.get("warm", True)))
    ready = {"port": server.port, "pid": os.getpid(),
             "shard": cfg["shard"], "replica": cfg.get("replica", 0),
             "generation": cfg.get("generation", 0)}
    tmp = cfg["ready_path"] + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(ready, f)
    os.replace(tmp, cfg["ready_path"])

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    try:
        stop.wait()
    finally:
        server.stop()
        # deadline-abandoned dispatch threads may still sit in XLA;
        # drain them so interpreter teardown doesn't race native code
        from .. import faults

        faults.drain_abandoned(timeout_s=5.0)
    return 0


class WorkerHandle:
    """One (shard, replica) subprocess: its Popen, address, generation.
    `alive` distinguishes a serving worker from a SIGKILLed corpse whose
    slot awaits respawn."""

    __slots__ = ("shard", "replica", "generation", "proc", "host",
                 "port", "pid")

    def __init__(self, shard: int, replica: int, generation: int,
                 proc, host: str, port: int, pid: int):
        self.shard = shard
        self.replica = replica
        self.generation = generation
        self.proc = proc
        self.host = host
        self.port = port
        self.pid = pid

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class ShardSet:
    """Spawn + manage the S x R worker grid as subprocesses.

    The grid is mutable under one lock: the chaos soak SIGKILLs replicas
    (`kill`) and brings them back (`respawn`, generation bumped) while
    the router keeps reading `addresses()` — a killed slot keeps its
    stale address until respawn (the router's breaker/deadline machinery
    is what handles the corpse, exactly as it would a remote host that
    dropped off the network).

    **Elastic membership (ISSUE 16).** The replica axis is ELASTIC:
    `grow()` adds one warm replica to every shard, `retire_replica()`
    drains one away. Every slot carries a lifecycle state —

        warming -> active -> draining -> retired

    — and every state transition bumps a MEMBERSHIP EPOCH (a counter
    concurrent walkers like `rolling_swap` use to detect that the grid
    changed under them and re-walk until it is stable). Two views of
    the grid exist: `addresses()` is the raw truth (stale corpse and
    draining addresses included — the health/chaos view), while
    `dispatchable()` nulls every non-active slot — the router dials
    ONLY dispatchable addresses, which is what makes the two contracts
    hold:

    - **warm-start**: a growing replica is `warming` (not dispatchable)
      until its ready file lands — and the worker writes that file only
      AFTER the precompile walk + residency pre-warm, so no routed
      request ever reaches a cold process (no compile storm, no breaker
      trip attributable to scale-up);
    - **drain-not-drop**: a retiring replica flips to `draining` (not
      dispatchable — new fan-outs exclude it immediately) but keeps
      serving; retire polls its in-flight count to zero before SIGTERM,
      so requests already dispatched to it complete normally. The one
      unavoidable race (an RPC from a pre-drain grid snapshot landing
      after the poll) is covered by the router's failover — the request
      is re-dispatched, never dropped, so `shed + served == submitted`
      holds across every membership change."""

    def __init__(self, index_dir: str, *, shards: int, replicas: int = 1,
                 layout: str = "sparse", deadline_s: float | None = None,
                 rundir: str | None = None, warm: bool = True,
                 max_concurrency: int = 4, max_queue: int = 16,
                 spawn_timeout_s: float = 120.0,
                 index_generation: int | None = None,
                 grow_nice: int = 5):
        if shards < 1 or replicas < 1:
            raise ValueError("shards and replicas must be >= 1")
        self.index_dir = index_dir
        # live indexes: pin spawns to one index generation (the upgrade
        # soak starts the fleet on gen A with gen B already prepared);
        # None = each worker resolves the current servable generation
        self.index_generation = index_generation
        self.shards = shards
        self.replicas = replicas
        self.layout = layout
        self.deadline_s = deadline_s
        self.warm = warm
        self.max_concurrency = max_concurrency
        self.max_queue = max_queue
        self.spawn_timeout_s = spawn_timeout_s
        # scale-up spawns warm up (interpreter + jax import + precompile)
        # at this nice level so they don't steal CPU from the live
        # serving path they exist to relieve; priority is restored once
        # the ready file lands (best-effort — needs CAP_SYS_NICE)
        self.grow_nice = grow_nice
        import tempfile

        self.rundir = rundir or tempfile.mkdtemp(prefix="tpu-ir-shardset-")
        os.makedirs(self.rundir, exist_ok=True)
        self._lock = threading.Lock()
        self._grid: list[list[WorkerHandle | None]] = [
            [None] * replicas for _ in range(shards)]
        # per-slot lifecycle, parallel to the grid; every transition
        # bumps the membership epoch (start() publishes "active")
        self._state: list[list[str]] = [
            ["warming"] * replicas for _ in range(shards)]
        self._epoch = 0
        # op-level membership log: ("up"|"down", shard, replica, epoch)
        self._events: list[tuple] = []

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ShardSet":
        """Spawn every worker CONCURRENTLY (each pays an interpreter +
        jax import + index load; serial spawn would multiply that by
        S*R), then wait for all ready files."""
        procs = [(s, r, self._spawn(s, r, generation=0))
                 for s in range(self.shards)
                 for r in range(self.replicas)]
        deadline = time.monotonic() + self.spawn_timeout_s
        for s, r, (proc, ready_path) in procs:
            handle = self._wait_ready(s, r, 0, proc, ready_path, deadline)
            with self._lock:
                self._grid[s][r] = handle
                self._state[s][r] = "active"
                self._epoch += 1
        return self

    def _cfg_paths(self, shard: int, replica: int, generation: int):
        base = f"worker-{shard}-{replica}-g{generation}"
        return (os.path.join(self.rundir, base + ".json"),
                os.path.join(self.rundir, base + ".ready"))

    def _spawn(self, shard: int, replica: int, *, generation: int,
               nice: int = 0):
        cfg_path, ready_path = self._cfg_paths(shard, replica, generation)
        # a reused (shard, replica, generation) slot must never read a
        # previous incarnation's ready file as its own
        if os.path.exists(ready_path):
            os.unlink(ready_path)
        cfg = {
            "index_dir": self.index_dir, "shard": shard,
            "num_shards": self.shards, "replica": replica,
            "generation": generation,
            "index_generation": self.index_generation,
            "layout": self.layout,
            "deadline_s": self.deadline_s, "warm": self.warm,
            "max_concurrency": self.max_concurrency,
            "max_queue": self.max_queue, "port": 0,
            "ready_path": ready_path,
        }
        with open(cfg_path, "w", encoding="utf-8") as f:
            json.dump(cfg, f)
        log = open(os.path.join(
            self.rundir, f"worker-{shard}-{replica}.log"), "ab")
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m", "tpu_ir.serving.shardset",
                 cfg_path],
                stdin=subprocess.PIPE, stdout=log, stderr=log,
                cwd=os.getcwd(),
                preexec_fn=(lambda: os.nice(nice)) if nice else None)
        finally:
            log.close()  # the child holds its own descriptor
        return proc, ready_path

    def _wait_ready(self, shard: int, replica: int, generation: int,
                    proc, ready_path: str, deadline: float):
        while time.monotonic() < deadline:
            if os.path.exists(ready_path):
                with open(ready_path, encoding="utf-8") as f:
                    ready = json.load(f)
                return WorkerHandle(shard, replica, generation, proc,
                                    "127.0.0.1", int(ready["port"]),
                                    int(ready["pid"]))
            if proc.poll() is not None:
                raise RuntimeError(
                    f"worker {shard}/{replica} died during startup "
                    f"(rc={proc.returncode}); see "
                    f"{self.rundir}/worker-{shard}-{replica}.log")
            time.sleep(READY_POLL_S)
        proc.kill()
        raise TimeoutError(
            f"worker {shard}/{replica} not ready within "
            f"{self.spawn_timeout_s}s")

    def kill(self, shard: int, replica: int, sig=signal.SIGKILL) -> int:
        """SIGKILL one replica (the chaos primitive). Returns the pid it
        killed. The slot keeps its handle (and stale address) — exactly
        what a crashed remote host looks like to the router."""
        with self._lock:
            h = self._grid[shard][replica]
        if h is None or h.proc is None:
            raise RuntimeError(f"no live worker at {shard}/{replica}")
        h.proc.send_signal(sig)
        h.proc.wait(timeout=30.0)
        return h.pid

    def respawn(self, shard: int, replica: int) -> WorkerHandle:
        """Bring a killed replica back with a bumped generation (fresh
        process, fresh port). The router notices via addresses()."""
        with self._lock:
            old = self._grid[shard][replica]
            generation = (old.generation + 1) if old else 0
        proc, ready_path = self._spawn(shard, replica,
                                       generation=generation)
        handle = self._wait_ready(
            shard, replica, generation, proc, ready_path,
            time.monotonic() + self.spawn_timeout_s)
        with self._lock:
            self._grid[shard][replica] = handle
            self._state[shard][replica] = "active"
            self._epoch += 1
        from ..obs import get_registry

        get_registry().incr("router.worker_respawn")
        return handle

    def set_index_generation(self, generation: int | None) -> None:
        """Re-pin the generation FUTURE spawns load (rolling_swap calls
        this after a live-index handoff so a later chaos respawn comes
        back on the new corpus, not the pinned old one)."""
        with self._lock:
            self.index_generation = generation

    # -- elastic membership (ISSUE 16) -------------------------------------

    def epoch(self) -> int:
        """The membership epoch: bumped on EVERY grid/state transition
        (publish, respawn, drain begin, retire). A concurrent walker
        (rolling_swap) snapshots it before a pass and re-walks until a
        full pass observes no change — the convergence handshake that
        keeps swap-during-scale zero-stale."""
        with self._lock:
            return self._epoch

    def lifecycle(self) -> list:
        """[shard][replica] -> lifecycle state string (warming / active
        / draining / retired) — the /healthz + router drain-awareness
        view, parallel to addresses()."""
        with self._lock:
            return [list(row) for row in self._state]

    def events(self) -> list:
        """Op-level membership log: ("up"|"down", shard, replica,
        epoch) per replica that entered/left the dispatch grid."""
        with self._lock:
            return list(self._events)

    def dispatchable(self) -> list:
        """addresses() with every non-active slot nulled — the view the
        router dials. A draining replica disappears from here the
        instant its drain begins (new fan-outs exclude it; its breaker
        sees no probes, the hedge p99 no samples) while addresses()
        keeps showing it to health/chaos tooling until it exits."""
        with self._lock:
            return [[h.addr if h and st == "active" else None
                     for h, st in zip(row, states)]
                    for row, states in zip(self._grid, self._state)]

    def grow(self) -> list:
        """Add one WARM replica to every shard: spawn concurrently (the
        start() rationale), wait for every ready file — written only
        after the worker's precompile walk + residency pre-warm — and
        only then publish the handles into the dispatch grid. Returns
        the [(shard, replica)] slots added. Reuses the lowest retired
        slot per shard (spawn generation bumped past the retiree's) so
        a breathing workload doesn't widen the grid without bound."""
        from ..obs import get_registry

        t0 = time.perf_counter()
        slots: list = []
        with self._lock:
            for s in range(self.shards):
                row, states = self._grid[s], self._state[s]
                for r, st in enumerate(states):
                    if st == "retired":
                        gen = (row[r].generation + 1) if row[r] else 0
                        break
                else:
                    r, gen = len(row), 0
                    row.append(None)
                    states.append("warming")
                states[r] = "warming"
                self._epoch += 1
                slots.append((s, r, gen))
        # warm up at lower CPU priority: on a saturated host a full-speed
        # spawn (interpreter + jax import + precompile) steals cycles
        # from the very serving path the scale-up exists to relieve
        procs = [(s, r, g,
                  self._spawn(s, r, generation=g, nice=self.grow_nice))
                 for s, r, g in slots]
        deadline = time.monotonic() + self.spawn_timeout_s
        added = []
        for s, r, g, (proc, ready_path) in procs:
            handle = self._wait_ready(s, r, g, proc, ready_path, deadline)
            if self.grow_nice:
                try:  # restore full priority before it takes traffic
                    os.setpriority(os.PRIO_PROCESS, handle.pid, 0)
                except (OSError, AttributeError):
                    pass  # no CAP_SYS_NICE: it serves niced, still warm
            with self._lock:
                # the swap-during-scale gate: if a rolling swap re-pinned
                # the index generation while this worker was loading the
                # OLD pin, reload it onto the current one BEFORE it can
                # serve a single routed request
                pinned = self.index_generation
            if pinned is not None and pinned != self._worker_index_gen(
                    handle):
                rpc_post(handle.addr, "reload", {"generation": pinned},
                         timeout_s=self.spawn_timeout_s)
            with self._lock:
                self._grid[s][r] = handle
                self._state[s][r] = "active"
                self._epoch += 1
                self._events.append(("up", s, r, self._epoch))
            added.append((s, r))
        reg = get_registry()
        reg.incr("scale.up", len(added))
        reg.observe("scale.warmup_ms", time.perf_counter() - t0)
        return added

    def _worker_index_gen(self, handle) -> int | None:
        """The index generation a just-readied worker actually loaded
        (None when unreadable — the caller's reload is then a no-op
        guard against a pin the worker already satisfies)."""
        try:
            w = get_worker_health(handle.addr, 2.0).get("worker") or {}
            g = w.get("index_generation")
            return None if g is None else int(g)
        except Exception:  # noqa: BLE001 — unreadable = don't reload
            return None

    def begin_drain(self, shard: int, replica: int) -> WorkerHandle:
        """Flip one active replica to `draining`: it leaves
        dispatchable() (the router stops dialing it) but keeps serving
        whatever is already in flight. Returns its handle."""
        with self._lock:
            h = self._grid[shard][replica]
            st = self._state[shard][replica]
            if h is None or st != "active":
                raise RuntimeError(
                    f"cannot drain {shard}/{replica}: state={st}")
            self._state[shard][replica] = "draining"
            self._epoch += 1
        return h

    def retire_replica(self, shard: int, replica: int, *,
                       drain_timeout_s: float = 30.0) -> dict:
        """Drain-not-drop retirement: begin_drain (dispatch stops
        immediately), poll the worker's admitted population (executing
        + queued) to zero, then SIGTERM and mark the slot `retired`.
        A replica SIGKILLed mid-drain (chaos) just ends the poll early
        — its in-flight requests fail over at the router and are still
        served or shed, never dropped. Returns the drain report."""
        from ..obs import get_registry

        t0 = time.perf_counter()
        with self._lock:
            already_draining = self._state[shard][replica] == "draining"
            h = self._grid[shard][replica] if already_draining else None
        if h is None:
            h = self.begin_drain(shard, replica)
        inflight_peak = 0
        zeros = 0
        deadline = time.monotonic() + max(drain_timeout_s, 0.1)
        killed_mid_drain = False
        settled = False
        while time.monotonic() < deadline:
            if not h.alive:
                killed_mid_drain = True
                break
            try:
                w = get_worker_health(h.addr, 1.0).get("worker") or {}
                admitted = (int(w.get("in_flight", 0))
                            + int(w.get("queued", 0)))
            except Exception:  # noqa: BLE001
                if not h.alive:  # died between the alive check and the
                    killed_mid_drain = True  # health read — still a kill
                else:
                    settled = True  # unreachable = nothing left in
                break  # flight we can observe; stop waiting
            inflight_peak = max(inflight_peak, admitted)
            if admitted == 0:
                zeros += 1
                if zeros >= 2:  # two consecutive empty reads: settled
                    settled = True
                    break
            else:
                zeros = 0
            time.sleep(0.05)
        drained_clean = settled and not killed_mid_drain
        if h.proc is not None and h.proc.poll() is None:
            h.proc.terminate()
            try:
                h.proc.wait(timeout=15.0)
            except subprocess.TimeoutExpired:
                h.proc.kill()
                h.proc.wait(timeout=10.0)
        with self._lock:
            self._state[shard][replica] = "retired"
            self._epoch += 1
            self._events.append(("down", shard, replica, self._epoch))
        reg = get_registry()
        reg.incr("scale.down")
        reg.incr("scale.drain_inflight", inflight_peak)
        drain_s = time.perf_counter() - t0
        reg.observe("scale.drain_ms", drain_s)
        return {"shard": shard, "replica": replica,
                "drain_s": round(drain_s, 3),
                "inflight_peak": inflight_peak,
                "drained_clean": drained_clean,
                "killed_mid_drain": killed_mid_drain}

    def active_replicas(self, shard: int | None = None) -> int:
        """Active (dispatchable) replica count — for one shard, or the
        MINIMUM across shards (the fleet's effective replication; the
        autoscaler's clamp input) when shard is None."""
        with self._lock:
            counts = [sum(1 for st in states if st == "active")
                      for states in self._state]
        if shard is not None:
            return counts[shard]
        return min(counts) if counts else 0

    def addresses(self) -> list:
        """[shard][replica] -> "host:port" — the raw topology truth
        (re-read per request, so respawned workers are picked up).
        Corpse and draining slots keep their addresses here; the
        router dials `dispatchable()` instead."""
        with self._lock:
            return [[h.addr if h else None for h in row]
                    for row in self._grid]

    def handles(self) -> list:
        with self._lock:
            return [list(row) for row in self._grid]

    def stop(self) -> None:
        """Terminate every worker (idempotent; corpses are fine)."""
        with self._lock:
            handles = [h for row in self._grid for h in row if h]
        for h in handles:
            if h.proc is not None and h.proc.poll() is None:
                h.proc.terminate()
        deadline = time.monotonic() + 15.0
        for h in handles:
            if h.proc is None:
                continue
            try:
                h.proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                h.proc.kill()
                h.proc.wait(timeout=10.0)
            if h.proc.stdin:
                try:
                    h.proc.stdin.close()
                except OSError:
                    pass

    def __enter__(self) -> "ShardSet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


if __name__ == "__main__":  # pragma: no cover — subprocess entry
    sys.exit(worker_main(sys.argv[1]))
