"""Continuous micro-batching: coalesce concurrent queries into one
padded device dispatch (ISSUE 9; ROADMAP 3).

Every BENCH row tells the same story: query p50 sits at ~`device_rtt_ms`
at every corpus size — latency is a fixed per-dispatch round trip, and
each caller pays it ALONE. The reference engine answered one query per
JVM invocation (PAPER.md §0), so it never faced the question; LLM
serving did, and answered with continuous batching (Orca-style
iteration-level scheduling). This module is that trick for retrieval:

    callers ──► admission ──► COALESCER ──► one padded kernel call ──► demux
                (PR 2)        (this file)   (Scorer.search_batch)

**Leader-follower combining, no owned threads.** The frontend owns no
worker pool (nothing to shut down, nothing to leak — the PR 2 design
rule), so the dispatcher is elected: the first caller to arrive while no
dispatch is in flight becomes the LEADER, drains every compatible queued
request into one batch, dispatches, and demuxes results to the waiting
FOLLOWERS via per-slot events. While a dispatch is in flight, new
arrivals queue — the in-flight window IS the coalescing window, so under
concurrency batches fill naturally with ZERO added wait; an idle arrival
dispatches immediately (`batch.solo_flush`), which is why the solo-query
path cannot regress. `TPU_IR_BATCH_WAIT_MS` optionally lets a PROMOTED
leader linger toward the next rung (bounded, default 0).

**The rung ladder.** Batches are padded (with -1 query rows inside the
scorer — exact 0.0 score contribution, pinned by the explain suite) to a
small ladder of compiled batch sizes (`TPU_IR_BATCH_LADDER`, default
1/4/16/64) and ONE pinned query width (`TPU_IR_BATCH_WIDTH`), so content
cannot mint per-batch XLA programs; `precompile()` walks the ladder at
frontend start so no caller ever eats a compile. The query-side device
buffer is donated on capable backends (`TPU_IR_BATCH_DONATE`,
ops/scoring.py `*_dq` twins); the index stays resident.

**Per-request semantics survive inside a shared batch** (tag, don't
drop): requests coalesce only with an identical BatchKey (k, scoring,
rerank, hot_only, force_host — everything that changes the traced
program or the serving route), while service level, explain depth, queue
wait and occupancy are tagged PER SLOT into results and querylog
entries. The dispatch deadline stays the scorer-level per-batch bound
all slots share — a slot's coalescing wait is bounded separately and
never charged against a batch-mate (the soak invariant: degradation
within one batch is uniform, from the shared dispatch, never from a
mate's slot).
"""

from __future__ import annotations

import threading
import time
from typing import NamedTuple

from .. import obs
from ..obs import disttrace, get_registry
from ..utils import envvars


class BatchKey(NamedTuple):
    """Everything that must MATCH for two requests to share one kernel
    call: k and scoring/rerank select the traced program, hot_only and
    force_host select the serving route. Mismatched arrivals stay queued
    for the next leader (FIFO — no starvation: the next leader is always
    the oldest queued slot, so its key is served next)."""

    k: int
    scoring: str
    rerank: int | None
    hot_only: bool
    force_host: bool


class _Slot:
    """One enqueued request. `state` transitions under the scheduler
    lock: None (queued) -> "lead" (promoted to dispatcher) -> taken into
    a batch -> "done"/"error"; or None -> "abandoned" (wait timeout
    while still queued). Results are written by the leader before the
    event is set — the event is the publication barrier."""

    __slots__ = ("text", "key", "explain_k", "level", "queue_depth",
                 "t_enqueue", "event", "state", "result", "error", "ctx")

    def __init__(self, text: str, key: BatchKey, explain_k: int,
                 level: str, queue_depth: int):
        self.text = text
        self.key = key
        self.explain_k = explain_k
        self.level = level
        self.queue_depth = queue_depth
        self.t_enqueue = time.perf_counter()
        self.event = threading.Event()
        self.state = None
        self.result = None
        self.error = None
        # the submitter's distributed-trace context, captured on ITS
        # thread — the leader executes this slot on a different thread,
        # where thread-local current() would read the leader's trace
        self.ctx = disttrace.current()


# rungs above this are dropped from the DEFAULT ladder on backends where
# per-row kernel cost is real (CPU-class): a 17-query burst padding to
# 64 pays 47 rows of actual compute there, while on a TPU the padded
# rows ride ~free on the MXU behind one fixed RTT (the ISSUE 9 sweep
# measured the split; ROADMAP 3 named this follow-up)
_CPU_MAX_RUNG = 16


def batch_ladder() -> tuple:
    """The compiled batch-size rungs, parsed from TPU_IR_BATCH_LADDER
    (sorted, deduped, all >= 1). A malformed spec raises — a silently
    empty ladder would disable coalescing without a trace.

    Adaptive default: when the variable is UNSET, CPU-class backends
    drop rungs above 16 (padded rows cost real compute where the kernel
    is compute-bound, so the top rung buys occupancy the hardware makes
    you pay for). An explicit TPU_IR_BATCH_LADDER always wins — the
    probe only picks the default."""
    spec = envvars.get_str("TPU_IR_BATCH_LADDER")
    try:
        rungs = sorted({max(1, int(p)) for p in spec.split(",") if p.strip()})
    except ValueError:
        raise ValueError(
            f"TPU_IR_BATCH_LADDER={spec!r}: expected comma-separated "
            "integers like '1,4,16,64'") from None
    if not rungs:
        raise ValueError("TPU_IR_BATCH_LADDER is empty")
    if not envvars.is_set("TPU_IR_BATCH_LADDER"):
        from ..search.scorer import _rtt_dominated_backend

        if not _rtt_dominated_backend():
            rungs = [r for r in rungs if r <= _CPU_MAX_RUNG] or rungs[:1]
    return tuple(rungs)


class CoalescingScheduler:
    """The coalescer between AdmissionController and device dispatch —
    see the module docstring for the protocol. One instance per
    ServingFrontend; thread-safe; owns no threads."""

    # leader poll granularity while lingering toward a fuller rung
    _POLL_S = 0.0005

    def __init__(self, scorer, *, deadline_s: float | None = None,
                 wait_ms: float | None = None, ladder: tuple | None = None,
                 width: int | None = None):
        self._scorer = scorer
        self._deadline_s = deadline_s
        self._wait_s = (envvars.get_float("TPU_IR_BATCH_WAIT_MS")
                        if wait_ms is None else max(0.0, wait_ms)) / 1e3
        # a caller-supplied ladder gets the same normalization the env
        # path applies (sorted ascending, deduped, >= 1): _take_batch /
        # _rung / _linger all assume ascending order — an unsorted
        # tuple would silently cap batches at ladder[-1] slots
        self._ladder = (tuple(sorted({max(1, int(r)) for r in ladder}))
                        if ladder else batch_ladder())
        width = (envvars.get_int("TPU_IR_BATCH_WIDTH")
                 if width is None else max(1, width))
        # normalize to the pow2 bucket analyze_queries will actually
        # emit for this floor — otherwise a width of e.g. 12 would
        # precompile (rung, 12) shapes while serving dispatches
        # (rung, 16), silently defeating the whole ladder precompile
        self._width = 1 << (int(width) - 1).bit_length()
        self._lock = threading.Lock()
        self._queue: list[_Slot] = []
        self._dispatching = False   # exactly one leader token
        # control-plane stats (served via frontend.stats() -> /healthz)
        self._batches = 0
        self._coalesced = 0
        self._solo = 0
        self._last_occupancy = 0
        self._max_occupancy = 0

    # -- the caller surface ------------------------------------------------

    def submit(self, text: str, *, k: int, scoring: str,
               rerank: int | None, hot_only: bool, force_host: bool,
               level: str, queue_depth: int = 0, explain_k: int = 0):
        """Serve one query through the coalescer; returns its
        SearchResult (per-slot tagged), raises whatever the shared
        dispatch raised. Blocks the calling thread — concurrency is the
        caller population's, bounded by admission upstream."""
        if '"' in text:
            raise ValueError("phrase queries cannot ride a coalesced "
                             "batch (host-scored); route them solo")
        slot = _Slot(text, BatchKey(k, scoring, rerank, bool(hot_only),
                                    bool(force_host)),
                     explain_k, level, queue_depth)
        with self._lock:
            self._queue.append(slot)
            lead = not self._dispatching
            if lead:
                self._dispatching = True
        if lead:
            return self._lead(slot, promoted=False)
        return self._follow(slot)

    def _follow(self, slot: _Slot):
        """Wait for the leader to deliver — or for a promotion to
        leadership when the previous batch completes first."""
        base = self._deadline_s if self._deadline_s else 0.0
        timeout = max(base * 4.0, 30.0) + self._wait_s
        deadline = time.monotonic() + timeout
        promoted = False
        while True:
            slot.event.wait(min(5.0, max(0.05, deadline - time.monotonic())))
            with self._lock:
                if slot.state == "lead":
                    slot.event.clear()
                    slot.state = None
                    promoted = True
                    break  # lead outside the lock
                if slot.state in ("done", "error"):
                    break
                if time.monotonic() >= deadline:
                    if slot in self._queue:
                        # still queued: abandon structurally (the caller
                        # gets an error, conservation holds upstream)
                        self._queue.remove(slot)
                        slot.state = "abandoned"
                        raise RuntimeError(
                            "coalesced request timed out waiting for a "
                            f"dispatch slot after {timeout:.1f}s")
                    # taken into an executing batch: the leader WILL
                    # deliver (or error) — extend, mirroring a solo
                    # caller blocked in its own dispatch
                    deadline = time.monotonic() + timeout
        if promoted:
            return self._lead(slot, promoted=True)
        if slot.error is not None:
            raise slot.error
        return slot.result

    # -- the leader --------------------------------------------------------

    def _lead(self, slot: _Slot, *, promoted: bool):
        """Run one batch as its dispatcher, then hand the token to the
        next queued slot (or release it). The token is held from
        election to hand-off, so exactly one batch collects/dispatches
        at a time — arrivals during OUR dispatch are the NEXT batch."""
        try:
            if promoted:
                self._linger(slot.key)
            with self._lock:
                batch = self._take_batch(slot)
            self._execute(batch)
        finally:
            with self._lock:
                nxt = self._queue[0] if self._queue else None
                if nxt is None:
                    self._dispatching = False
                else:
                    nxt.state = "lead"
                    nxt.event.set()
        if slot.error is not None:
            raise slot.error
        return slot.result

    def _linger(self, key: BatchKey) -> None:
        """The bounded coalescing wait (TPU_IR_BATCH_WAIT_MS): a
        PROMOTED leader may linger briefly so near-simultaneous arrivals
        make the batch — bounded, and skipped entirely for idle solo
        arrivals (they dispatch immediately; the <10% solo-regression
        acceptance bound rides on that)."""
        if self._wait_s <= 0.0:
            return
        top = self._ladder[-1]
        deadline = time.perf_counter() + self._wait_s
        while time.perf_counter() < deadline:
            with self._lock:
                if sum(1 for s in self._queue if s.key == key) >= top:
                    return
            time.sleep(self._POLL_S)

    def _take_batch(self, lead_slot: _Slot) -> list[_Slot]:
        """Drain (under the lock) every queued slot sharing the leader's
        key, FIFO, up to the top rung. The leader's own slot rides the
        same rule — it is always the oldest matching slot or the
        freshly promoted queue head."""
        top = self._ladder[-1]
        batch, rest = [], []
        for s in self._queue:
            if s.key == lead_slot.key and len(batch) < top:
                batch.append(s)
            else:
                rest.append(s)
        self._queue[:] = rest
        return batch

    def _rung(self, n: int) -> int:
        """Smallest ladder rung >= n (n never exceeds the top rung)."""
        for r in self._ladder:
            if r >= n:
                return r
        return self._ladder[-1]

    def _execute(self, slots: list[_Slot]) -> None:
        """One padded kernel call for the whole batch, demuxed per slot.
        Never raises: errors are delivered to every slot (the leader's
        own re-raises in _lead)."""
        t0 = time.perf_counter()
        b = len(slots)
        key = slots[0].key
        meta = [{"level": s.level,
                 "queue_depth": s.queue_depth,
                 "queue_wait_ms": round((t0 - s.t_enqueue) * 1e3, 3),
                 "batch_occupancy": b} for s in slots]
        for s, m in zip(slots, meta):
            if s.ctx is not None:
                # rides slot_meta into the scorer's querylog entry: the
                # entry is recorded on the LEADER's thread, where the
                # thread-local context is the leader's trace, not ours
                m["trace_id"] = s.ctx.trace_id
        reg = get_registry()
        reg.incr("batch.coalesced" if b > 1 else "batch.solo_flush")
        if obs.enabled():
            # occupancy is a COUNT observed on the histogram's bucket
            # scale (1..top-rung lands exactly); wait is per-slot seconds
            reg.observe("batch.occupancy", float(b))
            for m in meta:
                reg.observe("batch.wait", m["queue_wait_ms"] / 1e3)
        t_dispatch = time.perf_counter()
        try:
            results = self._scorer.search_batch(
                [s.text for s in slots], k=key.k, scoring=key.scoring,
                rerank=key.rerank, deadline_s=self._deadline_s,
                force_host=key.force_host, hot_only=key.hot_only,
                explain_ks=[s.explain_k for s in slots],
                pad_to=self._rung(b), width_floor=self._width,
                rung_ladder=self._ladder,
                donate_queries=True, slot_meta=meta)
        except BaseException as e:  # delivered, not swallowed: every
            self._trace_batch(slots, meta, b, t_dispatch, error=repr(e))
            for s in slots:         # slot's caller re-raises it
                s.error = e
                s.state = "error"
                s.event.set()
            return
        self._trace_batch(slots, meta, b, t_dispatch)
        with self._lock:
            self._batches += 1
            if b > 1:
                self._coalesced += 1
            else:
                self._solo += 1
            self._last_occupancy = b
            self._max_occupancy = max(self._max_occupancy, b)
        for i, (s, res) in enumerate(zip(slots, results)):
            # exactly ONE slot per shared dispatch carries the breaker
            # vote: N slots each recording the SAME dispatch outcome
            # would turn one transient deadline miss at occupancy >=
            # breaker_threshold into an instant breaker trip (the
            # threshold is documented in consecutive DISPATCH failures)
            res.breaker_vote = i == 0
            s.result = res
            s.state = "done"
            s.event.set()

    def _trace_batch(self, slots: list[_Slot], meta: list[dict], b: int,
                     t_dispatch: float, error: str | None = None) -> None:
        """Re-parent the shared dispatch across every member trace: ONE
        `batch.dispatch` span id (the batch_id join) appears in each
        traced slot's trace, parented under THAT slot's own context, so
        a follower's waterfall shows the leader's kernel call it rode —
        plus a per-slot `batch.slot` child carrying queue_wait /
        occupancy. No-op when no member carries a context."""
        traced = [(i, s) for i, s in enumerate(slots) if s.ctx is not None]
        if not traced:
            return
        dispatch_ms = (time.perf_counter() - t_dispatch) * 1e3
        start_ms = time.time() * 1e3 - dispatch_ms
        batch_id = disttrace.new_span_id()
        leader_trace = (slots[0].ctx.trace_id
                        if slots[0].ctx is not None else None)
        for i, s in traced:
            disttrace.add_span(
                s.ctx.trace_id, "batch.dispatch", span_id=batch_id,
                parent_id=s.ctx.span_id, start_ms=start_ms,
                dur_ms=dispatch_ms,
                attrs={"batch_id": batch_id, "occupancy": b,
                       "leader_trace": leader_trace,
                       "leader": i == 0},
                error=error)
            disttrace.add_span(
                s.ctx.trace_id, "batch.slot", parent_id=batch_id,
                start_ms=start_ms, dur_ms=dispatch_ms,
                attrs={"slot": i,
                       "queue_wait_ms": meta[i]["queue_wait_ms"],
                       "batch_occupancy": b})

    # -- warm-up + introspection -------------------------------------------

    def precompile(self, scorings=("tfidf", "bm25"), *,
                   ks: tuple = (10,)) -> int:
        """Compile every program steady-state serving can dispatch — the
        whole `rungs x {skip, full, hot_only} x scorings` universe at
        the pinned width (the coalesced path's _topk_uniform pads each
        scheduled group to a ladder rung, so this set is CLOSED: batch
        content cannot mint a shape outside it; hot_only is included so
        the ladder stepping down under overload — the one moment a
        compile stall hurts most — hits a warm kernel too) — so no
        caller ever eats an XLA compile on the topk path. Returns the
        number of warm dispatches. Driven through the scorer's kernel
        dispatch directly: a synthetic all-PAD batch cannot steer the
        content-dependent scheduler into the full-kernel variant, so the
        public search path cannot warm it. Known gaps: `k` is a static
        kernel argument, so only the depths in `ks`
        (ServingConfig.precompile_ks) are warmed — a caller-chosen k
        outside that set compiles once on first use; likewise rerank
        batches (cosine stage), whose candidate count is caller-chosen.

        Rungs are capped at the scorer's SCORE_BUDGET block size: a
        rung above it is dispatched by _blocked_dispatch as (block,
        width) slices in production, so THOSE are the shapes to warm —
        dispatching the raw rung would both compile a shape serving
        never uses and allocate the oversized score accumulator the
        budget exists to prevent."""
        import jax
        import numpy as np

        n = 0
        scorer = self._scorer
        variants = [{}]
        if scorer.layout == "sparse":
            variants = [{"skip_hot": True}, {}, {"hot_only": True}]
        elif scorer.layout == "sharded":
            variants = [{}, {"hot_only": True}]
        block = max(1, scorer._block_size())
        for rows in sorted({min(rung, block) for rung in self._ladder}):
            q = np.full((rows, self._width), -1, np.int32)
            for scoring in scorings:
                for k in ks:
                    for kw in variants:
                        out = scorer._topk_device(q, k, scoring,
                                                  donate=True, **kw)
                        jax.block_until_ready(out)
                        n += 1
        return n

    def snapshot(self) -> dict:
        """Control-plane state for frontend.stats() / /healthz."""
        with self._lock:
            return {
                "wait_ms": round(self._wait_s * 1e3, 3),
                "ladder": list(self._ladder),
                "width": self._width,
                "queued": len(self._queue),
                "dispatching": self._dispatching,
                "batches": self._batches,
                "coalesced": self._coalesced,
                "solo_flush": self._solo,
                "last_occupancy": self._last_occupancy,
                "max_occupancy": self._max_occupancy,
            }
