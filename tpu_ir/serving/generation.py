"""Generation swap orchestration: rolling upgrades + the swap bench.

The live index (index/segments.py, index/ingest.py) produces immutable
GENERATIONS; this module moves a SERVING fleet from one to the next
with zero downtime:

- in one process, `ServingFrontend.reload_generation()` is the whole
  story (load + warm outside the request path, publish as one
  reference swap — frontend.py);
- across the scatter-gather tier, `rolling_swap()` walks the worker
  grid replica by replica, POSTing /rpc/reload and confirming each
  worker's /healthz names the new generation before moving on. Every
  worker keeps SERVING its old generation until its own publish
  instant, so the fleet never has a dark replica; the router
  (serving/router.py) tolerates the resulting mixed-generation window
  by merging only the winning generation's responses per request and
  tagging the rest missing (partial) — every response names exactly
  one corpus snapshot.

`swap_microbench()` is the number behind the claim: serve a probe
stream while ingesting a delta and swapping, and report `swap_gap_ms` —
the widest gap between consecutive successful responses across the
swap window. Zero-downtime means that gap is ordinary request latency,
not a load-time outage; the row lands in BENCH_HISTORY.jsonl where
`tpu-ir bench-check` gates it direction-aware.
"""

from __future__ import annotations

import logging
import threading
import time

from ..obs import get_registry
from .shardset import get_worker_health, rpc_post

logger = logging.getLogger(__name__)


def rolling_swap(topology, generation: int | None = None, *,
                 reload_timeout_s: float = 300.0,
                 confirm: bool = True) -> dict:
    """Roll the worker fleet onto a new index generation, one replica
    at a time. `topology` is a ShardSet, a callable, or a static
    [shard][replica] address grid (the Router's own contract). Each
    worker loads + warms the new generation while STILL serving its
    old one (the reload RPC returns only after the worker's publish),
    and `confirm=True` re-reads /healthz to pin the handoff before the
    next replica starts — the rolling order is what bounds the
    mixed-generation window to the walk itself.

    Dead/unreachable replicas are skipped and reported (`failed`) —
    a rolling upgrade must not wedge on the corpse the chaos schedule
    just SIGKILLed; the respawn path brings it back on the new
    generation.

    **Swap-during-scale (ISSUE 16).** An elastic topology can grow,
    shrink and respawn replicas WHILE the walk runs — a replica that
    publishes after the snapshot this walk took would silently stay on
    the old generation. Two mechanisms close that window: when the
    target generation is known up front it is pinned onto the topology
    BEFORE the walk (every spawn from that instant loads the new
    generation — and ShardSet.grow re-checks the pin before a new
    replica enters the dispatch grid), and after each pass the
    topology's membership EPOCH is re-read: if it moved, the grid is
    re-walked (already-confirmed addresses skipped) until one full
    pass observes a stable epoch — so a swap concurrent with any
    membership change still ends zero-stale."""
    def read_grid():
        if callable(topology):
            return topology()
        if hasattr(topology, "addresses"):
            return topology.addresses()
        return [list(row) for row in topology]

    epoch_fn = getattr(topology, "epoch", None)
    lifecycle_fn = getattr(topology, "lifecycle", None)
    t0 = time.perf_counter()
    swapped, failed = [], []
    confirmed: set = set()
    result_gen = generation
    if generation is not None \
            and hasattr(topology, "set_index_generation"):
        # pin FIRST: a replica spawning concurrently with this walk
        # must load the new generation, not the old pin
        topology.set_index_generation(int(generation))
    rounds = 0
    while True:
        rounds += 1
        epoch_before = epoch_fn() if epoch_fn else None
        grid = read_grid()
        life = lifecycle_fn() if lifecycle_fn else None
        for shard, row in enumerate(grid):
            for replica, addr in enumerate(row):
                if not addr or addr in confirmed:
                    continue
                if life is not None:
                    st = life[shard][replica] if (
                        shard < len(life)
                        and replica < len(life[shard])) else None
                    if st in ("draining", "retired"):
                        # a replica LEAVING the fleet is not rolled: a
                        # draining worker only finishes old in-flight
                        # work (bounded window), a retired slot is a
                        # corpse
                        continue
                payload = ({} if generation is None
                           else {"generation": int(generation)})
                try:
                    out = rpc_post(addr, "reload", payload,
                                   reload_timeout_s)
                    result_gen = out.get("generation", result_gen)
                    if confirm:
                        h = get_worker_health(addr, 10.0)
                        got = (h.get("worker")
                               or {}).get("index_generation")
                        if result_gen is not None and got != result_gen:
                            raise RuntimeError(
                                f"worker {addr} reports index_"
                                f"generation {got!r} after reload to "
                                f"{result_gen}")
                    swapped.append((shard, replica, addr))
                    confirmed.add(addr)
                except Exception as e:  # noqa: BLE001 — a dead replica
                    # must not wedge the roll; it respawns on the new
                    # generation
                    logger.warning("rolling swap: %s failed: %r",
                                   addr, e)
                    failed.append((shard, replica, addr, repr(e)))
        if epoch_fn is None or epoch_fn() == epoch_before:
            break
        if rounds >= 8:
            logger.warning("rolling swap: membership still churning "
                           "after %d passes; stopping (the grow gate "
                           "re-pins late spawns)", rounds)
            break
    if hasattr(topology, "set_index_generation"):
        # future respawns must come back on the NEW generation
        topology.set_index_generation(result_gen)
    return {"generation": result_gen,
            "swapped": swapped, "failed": failed, "rounds": rounds,
            "wall_s": round(time.perf_counter() - t0, 3)}


# ---------------------------------------------------------------------------
# the ingest -> swap micro-bench
# ---------------------------------------------------------------------------

_BENCH_WORDS = ("salmon fishing river bears honey quick brown fox lazy "
                "dog market investor asset bond stock season rain "
                "forest".split())


def _bench_doc(i: int) -> tuple[str, str]:
    text = " ".join(_BENCH_WORDS[(i + j) % len(_BENCH_WORDS)]
                    for j in range(4 + (i % 6)))
    return f"SWAP-{i:05d}", text


def swap_microbench(live_dir: str, *, base_docs: int = 64,
                    delta_docs: int = 16, probe_s: float = 1.0,
                    num_shards: int = 4) -> dict:
    """Measure the serving cost of one ingest -> compact -> swap cycle.

    Builds (or reuses) a live index at `live_dir`, serves generation A
    through a frontend while a probe thread issues back-to-back
    queries, then ingests a delta, compacts to generation B and calls
    `frontend.reload_generation()`. Reported:

    - `swap_gap_ms`   — widest gap between consecutive successful probe
                        responses across the swap window (the
                        zero-downtime claim, measured);
    - `swap_staleness_ms` — reload call to first generation-B-tagged
                        response (how long the new corpus takes to
                        reach traffic: load + warm + publish);
    - `swap_wall_s`   — the whole reload_generation call.

    The probe thread is owned and joined HERE (bench harness, not
    library serving code — the PR-2 no-owned-threads rule applies to
    the frontend, not its benches)."""
    from ..index.ingest import IngestWriter
    from ..index.segments import LiveIndex, is_live
    from ..search.scorer import Scorer
    from .frontend import ServingConfig, ServingFrontend

    if not is_live(live_dir):
        LiveIndex.create(live_dir, num_shards=num_shards)
    live = LiveIndex.open(live_dir)
    with IngestWriter(live_dir, auto_merge=False) as w:
        existing = w._docs()
        for i in range(base_docs):
            docid, text = _bench_doc(i)
            if docid not in existing:
                w.add(docid, text)
        w.compact_all(note="swap-bench base")

    scorer_a = Scorer.load_generation(live_dir, layout="sparse")
    frontend = ServingFrontend(scorer_a, ServingConfig(
        max_concurrency=4, max_queue=16))

    # prepare generation B while A serves (exactly the production shape)
    with IngestWriter(live_dir, auto_merge=False) as w:
        for i in range(base_docs, base_docs + delta_docs):
            w.update(*_bench_doc(i))
        w.compact_all(note="swap-bench delta")
    gen_b = live.current_gen()

    texts = [" ".join(_BENCH_WORDS[i % len(_BENCH_WORDS)]
                      for i in range(j, j + 2)) for j in range(8)]
    for t in texts:  # warm every probe shape before measuring
        frontend.search(t, k=5, scoring="bm25")

    stop = threading.Event()
    events: list[tuple[float, int]] = []  # (completion time, generation)
    lock = threading.Lock()

    def probe() -> None:
        i = 0
        while not stop.is_set():
            try:
                res = frontend.search(texts[i % len(texts)], k=5,
                                      scoring="bm25")
                with lock:
                    events.append((time.perf_counter(), res.generation))
            except Exception:  # noqa: BLE001 — a shed during the swap
                # window would BE the finding; count it as a gap
                pass
            i += 1

    th = threading.Thread(target=probe, name="tpu-ir-swap-bench-probe")
    th.start()
    try:
        time.sleep(probe_s / 2)
        t_swap0 = time.perf_counter()
        frontend.reload_generation(generation=gen_b)
        t_swap1 = time.perf_counter()
        time.sleep(probe_s / 2)
    finally:
        stop.set()
        th.join(timeout=30.0)

    with lock:
        evs = list(events)
    window = [t for t, _ in evs
              if t_swap0 - 0.25 <= t <= t_swap1 + 0.25]
    gap_ms = 0.0
    prev = t_swap0 - 0.25
    for t in sorted(window) + [t_swap1 + 0.25]:
        gap_ms = max(gap_ms, (t - prev) * 1e3)
        prev = t
    first_b = next((t for t, g in evs if g == gen_b and t >= t_swap0),
                   None)
    gens_seen = sorted({g for _, g in evs})
    get_registry().set_gauge("generation.current", gen_b)
    return {
        "generation_a": scorer_a.generation,
        "generation_b": gen_b,
        "probes": len(evs),
        "generations_seen": gens_seen,
        "swap_gap_ms": round(gap_ms, 3),
        "swap_staleness_ms": (round((first_b - t_swap0) * 1e3, 3)
                              if first_b is not None else -1.0),
        "swap_wall_s": round(t_swap1 - t_swap0, 3),
        "num_docs_b": frontend.scorer.meta.num_docs,
    }
