"""Admission control: a bounded request queue in front of the scorer.

The load-shedding half of "The Tail at Scale": a server melting down does
the most damage by QUEUING — every queued request still burns its full
deadline after minutes of waiting, so by the time it runs, its caller has
long since retried (adding more load). The admission controller bounds
both dimensions up front:

- `max_concurrency` requests execute at once (a semaphore);
- at most `max_queue` more may WAIT for a slot;
- anything past that is shed IMMEDIATELY with a structured `Overloaded`
  rejection — the caller learns in microseconds, not after a timeout;
- a waiter that cannot get a slot within `queue_timeout_s` is shed too
  (its remaining deadline budget would be garbage anyway).

Shedding is the cheapest thing a server can do per request, which is why
it must happen before any analysis/dispatch work, at the one place that
can see the whole queue.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager


class Overloaded(RuntimeError):
    """Structured admission rejection: the request was shed WITHOUT being
    executed. Carries why (`reason`: 'queue_full' | 'queue_timeout' |
    'shed_level'), the queue depth observed at rejection, and the service
    level the ladder was at — everything a client needs for retry policy
    (back off; these are never partial results)."""

    def __init__(self, reason: str, *, queue_depth: int = 0,
                 level: str = "shed"):
        self.reason = reason
        self.queue_depth = queue_depth
        self.level = level
        super().__init__(
            f"overloaded ({reason}): request shed at service level "
            f"{level!r} with {queue_depth} request(s) queued")


class AdmissionController:
    """Bounded concurrency + bounded wait queue; everything else sheds."""

    def __init__(self, max_concurrency: int = 4, max_queue: int = 16):
        if max_concurrency < 1:
            raise ValueError("max_concurrency must be >= 1")
        if max_queue < 0:
            raise ValueError("max_queue must be >= 0")
        self.max_concurrency = max_concurrency
        self.max_queue = max_queue
        self._slots = threading.Semaphore(max_concurrency)
        self._lock = threading.Lock()
        self._waiting = 0
        self._executing = 0

    def queue_depth(self) -> int:
        """Requests currently waiting for an execution slot."""
        with self._lock:
            return self._waiting

    def in_flight(self) -> int:
        """Requests currently EXECUTING (admitted, slot held). With
        queue_depth() this is the whole admitted population — the
        drain handshake (ISSUE 16) terminates a retiring server only
        once both read zero, and the autoscaler reads occupancy
        (in_flight / max_concurrency) as its pressure signal."""
        with self._lock:
            return self._executing

    def pressure(self) -> float:
        """Queue occupancy in [0, 1] — the degradation ladder's input
        signal. 0 = nothing waiting, 1 = the wait queue is full (the
        next arrival sheds)."""
        with self._lock:
            return (self._waiting / self.max_queue if self.max_queue
                    else float(self._waiting > 0))

    @contextmanager
    def admit(self, queue_timeout_s: float | None = None):
        """Admit one request: yields holding an execution slot, raises
        Overloaded when the wait queue is full or the slot did not free
        within `queue_timeout_s` (None = wait indefinitely).

        A free slot is taken WITHOUT touching the wait queue, so only
        requests that actually have to wait count toward queue depth /
        pressure — and `max_queue=0` means "execute, never queue", not
        "shed everything"."""
        got = self._slots.acquire(blocking=False)
        if not got:
            with self._lock:
                if self._waiting >= self.max_queue:
                    raise Overloaded("queue_full",
                                     queue_depth=self._waiting)
                self._waiting += 1
            try:
                got = (self._slots.acquire(timeout=queue_timeout_s)
                       if queue_timeout_s is not None
                       else self._slots.acquire())
            finally:
                with self._lock:
                    self._waiting -= 1
                    depth = self._waiting
            if not got:
                raise Overloaded("queue_timeout", queue_depth=depth)
        with self._lock:
            self._executing += 1
        try:
            yield
        finally:
            with self._lock:
                self._executing -= 1
            self._slots.release()
