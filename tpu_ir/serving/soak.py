"""Concurrent chaos soak: mixed query traffic through the ServingFrontend
while a fault plan injects hangs and device losses.

This is the proof harness for the overload story, the concurrency
sibling of tests/test_faults.py's per-fault-class suite. One run drives
`threads` worker threads over a deterministic (seeded) mixed query set
and checks the serving invariants that single-request tests cannot:

- **no deadlock**: every request completes (or is shed) within the
  soak's wall-clock bound;
- **no cross-request corruption**: every response served at full level
  without degradation is BIT-IDENTICAL to a serial reference run of the
  same query (same docids, same float scores);
- **no silent degradation**: any response that differs from the
  reference carries a tag explaining why (degraded flag or a
  non-full service level);
- **conservation**: shed + served (+ errors, expected 0) equals
  submitted — no request vanishes.

Used by tests/test_serving.py (fast + slow variants), the
`tpu-ir serve-bench` CLI, and experiments/soak_serving.py.
"""

from __future__ import annotations

import logging
import math
import os
import random
import threading
import time
from concurrent.futures import FIRST_EXCEPTION, ThreadPoolExecutor, wait

from .. import faults, obs
from ..utils.report import recovery_counters
from .admission import Overloaded
from .frontend import ServingConfig, ServingFrontend

logger = logging.getLogger(__name__)

# default fault plan for chaos runs: occasional hangs long enough to trip
# any sane deadline, plus sporadic device losses — both sites fire on the
# per-block score dispatch, so concurrent requests race into them
DEFAULT_CHAOS_PLAN = ("score.hang:p=0.12:sleep=0.6,"
                      "score.device_loss:p=0.08,seed=1")

# completed-fraction before the closed-loop autoscaler (and its
# forecaster) starts ticking in the routed soak: the opening requests'
# JIT warmup pins occupancy at the cap for a second or two, and that
# transient is not load — scaling on it turns every A/B run into
# "grew at t=0" regardless of the arrival wave
_SCALE_WARMUP_FRAC = 0.05


def make_queries(scorer, n: int, seed: int = 0,
                 workload=None) -> list[dict]:
    """A deterministic mixed workload over the index's own vocabulary:
    1-3 term queries, tfidf/bm25 split, ~25% requesting the two-stage
    rerank. Seeded so a soak run is replayable.

    `workload` (ISSUE 15; serving/workload.py) reshapes the traffic:
    None defers to TPU_IR_WORKLOAD (default uniform = this function's
    historical draw, bit-reproducible), "zipf"/a Workload instance
    draws terms rank-skewed over the df-ordered vocabulary."""
    from .workload import resolve_workload

    wl = resolve_workload(scorer, workload, seed=seed)
    if wl is not None:
        return wl.make_queries(n, seed=seed)
    rng = random.Random(seed)
    terms = list(scorer.vocab.terms)
    if not terms:
        raise ValueError("scorer has an empty vocabulary")
    reqs = []
    for _ in range(n):
        text = " ".join(rng.choice(terms)
                        for _ in range(rng.randint(1, 3)))
        reqs.append({
            "text": text,
            "scoring": rng.choice(["tfidf", "bm25"]),
            "rerank": rng.choice([None, None, None, 25]),
            "k": 10,
        })
    return reqs


def _req_key(r: dict) -> tuple:
    return (r["text"], r["scoring"], r["rerank"], r["k"])


def _p99_ms(vals: list) -> float:
    if not vals:
        return -1.0
    vs = sorted(vals)
    return round(vs[min(len(vs) - 1,
                        int(round(0.99 * (len(vs) - 1))))], 3)


def _cache_counters_now() -> dict:
    from ..obs.registry import CACHE_COUNTER_NAMES

    reg = obs.get_registry()
    return {n: reg.get(n) for n in CACHE_COUNTER_NAMES}


def _cache_delta(before: dict) -> dict:
    """THIS run's result-cache activity (registry delta — repeated
    soaks in one process must not bleed), with the derived hit
    fraction the bench rows record per skew level."""
    now = _cache_counters_now()
    out = {n.split(".", 1)[1]: now[n] - before.get(n, 0) for n in now}
    looked = out["hit"] + out["miss"]
    out["hit_fraction"] = (round(out["hit"] / looked, 4)
                           if looked else 0.0)
    return out


def _walk_spans(nodes):
    """Depth-first over a stitched trace's span tree."""
    for n in nodes:
        yield n
        yield from _walk_spans(n.get("children", ()))


def _trace_shape_ok(st: dict, res) -> tuple[bool, str]:
    """Does ONE stitched trace match the response it explains? The
    checks are the causal claims the trace makes: one root (the router
    admission span), a verdict on every RPC attempt (won / lost /
    failed / cancelled / deadline — nothing vanishes), hedge spans
    exactly equal to the hedges the response reports, and for every
    shard that CONTRIBUTED a winning attempt plus that worker's own
    request span (the cross-process join actually happened)."""
    if len(st["roots"]) != 1:
        return False, "multi_root"
    spans = list(_walk_spans(st["roots"]))
    rpc = [s for s in spans if s["name"].startswith("rpc.")]
    if any(not s.get("attrs", {}).get("outcome") for s in rpc):
        return False, "attempt_without_outcome"
    hedged = sum(1 for s in rpc if s.get("attrs", {}).get("hedge"))
    if hedged < int(res.hedges):
        # >=, not ==: res.hedges counts only hedges whose shard ended
        # up CONTRIBUTING — a hedge fired on a shard that then missed
        # the deadline is exactly what the trace must still show
        return False, f"hedge_spans={hedged}<res.hedges={res.hedges}"
    won = {s["attrs"].get("shard") for s in rpc
           if s["name"] == "rpc.search"
           and s["attrs"].get("outcome") == "won"}
    if not set(res.shards_ok) <= won:
        return False, "contributing_shard_without_winning_attempt"
    worker_shards = set()
    for s in spans:
        svc = s.get("service", "")
        if s["name"] == "request" and svc.startswith("worker-s"):
            try:
                worker_shards.add(int(svc[8:].split("r", 1)[0]))
            except ValueError:
                pass
    if not set(res.shards_ok) <= worker_shards:
        return False, "contributing_shard_without_worker_spans"
    return True, ""


def _disttrace_eval(outcomes: list, reqs: list) -> dict:
    """The routed soak's distributed-trace invariant (ISSUE 18): every
    served, dispatched response joins via res.trace_id to exactly one
    stitched trace whose span population matches its fan-out + hedge +
    cross-process shape — and no partial/degraded/hedged (tail) trace
    is missing. Returns the report section; `violations` > 0 is a
    breach."""
    from ..obs import disttrace

    traced = untraced = stitch_missing = tail_missing = 0
    shape_bad = 0
    span_counts: list = []
    samples: list = []
    for out in outcomes:
        if out is None or out[0] != "ok":
            continue
        res = out[1]
        tid = getattr(res, "trace_id", None)
        if tid is None:
            # cache hits answer ahead of admission — nothing dispatched,
            # nothing minted
            untraced += 1
            continue
        traced += 1
        st = disttrace.stitch(tid)
        interesting = bool(res.partial or res.degraded or res.hedges)
        if st is None:
            stitch_missing += 1
            tail_missing += interesting
            if len(samples) < 5:
                samples.append({"trace_id": tid, "why": "no_stitch"})
            continue
        span_counts.append(st["span_count"])
        ok, why = _trace_shape_ok(st, res)
        if not ok:
            shape_bad += 1
            if len(samples) < 5:
                samples.append({"trace_id": tid, "why": why})
    return {
        "traced": traced,
        "untraced_served": untraced,
        "stitch_missing": stitch_missing,
        "tail_missing": tail_missing,
        "shape_violations": shape_bad,
        "violations": stitch_missing + shape_bad,
        "mean_spans": round(sum(span_counts) / len(span_counts), 2)
        if span_counts else 0.0,
        "violation_samples": samples,
    }


def _disttrace_overhead(mean_request_ms: float, n: int = 512) -> dict:
    """The ISSUE-18 overhead acceptance, measured synthetically: time
    the FULL per-request trace bookkeeping (mint, install, one attempt
    span + annotations, SLO record, store churn) per iteration and
    express it against this run's mean served latency — enabled and
    disabled paths both. The soak itself runs traced, so the enabled
    cost is also baked into its absolute latency numbers."""
    from ..obs import disttrace

    def per_req_ms() -> float:
        t0 = time.perf_counter()
        for _ in range(n):
            ctx = disttrace.mint()
            with disttrace.use(ctx):
                if ctx is not None:
                    c = disttrace.child(ctx)
                    sid = disttrace.add_span(
                        c.trace_id, "rpc.search", span_id=c.span_id,
                        parent_id=c.parent_id, attrs={"shard": 0})
                    disttrace.annotate(c.trace_id, sid, dur_ms=1.0,
                                       outcome="won")
                disttrace.slo_record("full", 1.0)
                if ctx is not None:
                    disttrace.drop(ctx.trace_id)
        return (time.perf_counter() - t0) * 1e3 / n

    was = disttrace.enabled()
    try:
        disttrace.configure(enabled=True)
        enabled_ms = per_req_ms()
        disttrace.configure(enabled=False)
        disabled_ms = per_req_ms()
    finally:
        disttrace.configure(enabled=was)
    base = max(mean_request_ms, 1e-6)
    return {
        "per_request_ms": round(enabled_ms, 6),
        "per_request_disabled_ms": round(disabled_ms, 6),
        "enabled_overhead_fraction": round(enabled_ms / base, 6),
        "disabled_overhead_fraction": round(disabled_ms / base, 6),
    }


def _serial_reference(scorer, reqs: list[dict]) -> dict:
    """Full-level serial results per distinct request, computed BEFORE
    any fault plan installs (also warms every compile cache, so the
    concurrent phase measures serving, not compilation)."""
    ref = {}
    for r in reqs:
        key = _req_key(r)
        if key in ref:
            continue
        res = scorer.search_batch([r["text"]], k=r["k"],
                                  scoring=r["scoring"],
                                  rerank=r["rerank"])[0]
        if res.degraded:
            raise RuntimeError("reference run degraded — clear the fault "
                               "plan before calling run_soak")
        ref[key] = list(res)
        obs.report_progress("reference", advance=1)
    return ref


def run_soak(scorer, *, threads: int = 8, queries: int = 240,
             seed: int = 0, fault_spec: str | None = DEFAULT_CHAOS_PLAN,
             config: ServingConfig | None = None,
             timeout_s: float = 120.0, pacing_s: float = 0.004,
             flight_dir: str | None = None,
             coalesce: bool = False, workload=None) -> dict:
    """Run the soak; returns the invariant report (no asserts here — the
    callers decide what is fatal; tests assert on the report fields).
    The report's `latency` section holds per-stage p50/p95/p99 for the
    CONCURRENT phase only (delta against the telemetry registry, so
    repeated runs in one process don't bleed into each other); on an
    invariant breach the flight recorder dumps the last traces +
    telemetry to `flight_dir` and the report carries the path.

    The scorer must be loaded and fault-plan-free on entry; the given
    `fault_spec` (None = no chaos) is installed only around the
    concurrent phase and cleared after.

    `coalesce=True` (ISSUE 9) runs the soak through the continuous
    micro-batching frontend: concurrent requests share padded kernel
    dispatches. All the PR 2 invariants must survive UNCHANGED, plus
    one batching-specific pin the report carries: within any shared
    batch, degradation is uniform (`batch_mixed_degraded` == 0 — the
    dispatch outcome is shared, so no slot can be charged a deadline a
    batch-mate's slow slot burned while it itself was served clean)."""
    from .workload import resolve_workload

    if faults.active() is not None:
        raise RuntimeError("a fault plan is already installed")
    wl = resolve_workload(scorer, workload, seed=seed)
    reqs = make_queries(scorer, queries, seed=seed, workload=wl)
    # JobTracker-style progress: /jobs shows the soak's reference and
    # concurrent phases live, with percent-complete over the request
    # count (obs/progress.py; the `tpu-ir serve-bench --metrics-port`
    # surface)
    job = obs.start_job(
        "soak", f"soak-{queries}q-{threads}t", phases=("reference",
                                                       "serve"),
        config={"threads": threads, "queries": queries, "seed": seed,
                "fault_spec": fault_spec})
    try:
        obs.report_progress("reference",
                            total=len({_req_key(r) for r in reqs}))
        reference = _serial_reference(scorer, reqs)
        obs.report_progress("serve", total=len(reqs))

        if config is None:
            cfg = ServingConfig(max_concurrency=4, max_queue=8,
                                deadline_s=0.25, breaker_threshold=4,
                                breaker_cooldown_s=0.2, coalesce=coalesce)
        elif coalesce and not config.coalesce:
            # coalesce=True must not be silently ignored just because a
            # caller also tuned the admission/breaker knobs
            from dataclasses import replace

            cfg = replace(config, coalesce=True)
        else:
            cfg = config
        frontend = ServingFrontend(scorer, cfg)
        recovery_before = recovery_counters().snapshot()
        hist_before = obs.get_registry().hist_state()
        cache_before = _cache_counters_now()
        results: list = [None] * len(reqs)

        def worker(i: int, r: dict) -> None:
            if pacing_s:
                # spread arrivals (seeded jitter): back-to-back submission of
                # the whole workload is a thundering herd, which the ladder
                # answers by shedding everything — pacing keeps the soak
                # exercising RECOVERY too, not just collapse. A workload
                # burst schedule compresses/stretches the jitter window
                # per request — the diurnal wave.
                scale = (wl.pacing_scale(i / max(len(reqs), 1))
                         if wl is not None else 1.0)
                time.sleep(random.Random(seed * 1_000_003 + i).random()
                           * pacing_s * threads * scale)
            try:
                results[i] = ("ok", frontend.search(
                    r["text"], k=r["k"], scoring=r["scoring"],
                    rerank=r["rerank"]))
                job.report("serve", advance=1, served=1)
            except Overloaded as e:
                results[i] = ("shed", e)
                job.report("serve", advance=1, shed=1)
            except BaseException as e:  # invariant: structured or nothing
                results[i] = ("error", e)
                job.report("serve", advance=1, errors=1)

        if fault_spec:
            faults.install(faults.parse_plan(fault_spec))
        t0 = time.perf_counter()
        wall_s = 0.0
        deadlocked = 0
        pool = ThreadPoolExecutor(max_workers=threads,
                                  thread_name_prefix="soak-worker")
        try:
            futs = [pool.submit(worker, i, r) for i, r in enumerate(reqs)]
            done, not_done = wait(futs, timeout=timeout_s,
                                  return_when=FIRST_EXCEPTION)
            wall_s = time.perf_counter() - t0
            deadlocked = len(not_done)  # governs teardown mode only
            for f in not_done:
                f.cancel()
        finally:
            # wait=False: a genuinely hung worker must surface as the
            # `deadlocked` count (and the test harness's thread-leak guard),
            # not hang the soak's own teardown
            pool.shutdown(wait=deadlocked == 0, cancel_futures=True)
            faults.clear()
            # abandoned deadline dispatches may still be sleeping in an
            # injected hang; drain them so nothing races process teardown
            faults.drain_abandoned(timeout_s=10.0)

        # -- invariant evaluation ---------------------------------------------
        # snapshot the outcome list ONCE: cancelled-but-running workers
        # (shutdown(wait=False) on deadlock) may still be writing. An entry
        # still None at snapshot time IS the deadlock count — it must not
        # also masquerade as an unstructured error
        outcomes = list(results)
        deadlocked = sum(1 for o in outcomes if o is None)
        served = shed = errors = degraded = 0
        levels: dict[str, int] = {}
        full_bitident = tagged_divergent = untagged_mismatches = 0
        error_reprs: list[str] = []
        for out, r in zip(outcomes, reqs):
            if out is None:
                continue
            state, payload = out
            if state == "shed":
                shed += 1
                continue
            if state == "error":
                errors += 1
                if len(error_reprs) < 5:
                    error_reprs.append(repr(payload))
                continue
            served += 1
            res = payload
            levels[res.level] = levels.get(res.level, 0) + 1
            degraded += bool(res.degraded)
            matches = list(res) == reference[_req_key(r)]
            if res.level == "full" and not res.degraded:
                if matches:
                    full_bitident += 1
                else:
                    # an untagged response that differs from the serial
                    # reference is the cross-request corruption this soak
                    # exists to catch
                    untagged_mismatches += 1
            elif not matches:
                tagged_divergent += 1

        fe_stats = frontend.stats()
        recovery_delta = {
            k: v - recovery_before.get(k, 0)
            for k, v in recovery_counters().snapshot().items()
            if v != recovery_before.get(k, 0)}
        report = {
            "submitted": len(reqs),
            "threads": threads,
            "served": served,
            "shed": shed,
            "errors": errors,
            "error_samples": error_reprs,
            "deadlocked": deadlocked,
            "degraded": degraded,
            "levels": levels,
            "full_bitidentical": full_bitident,
            "tagged_divergent": tagged_divergent,
            "untagged_mismatches": untagged_mismatches,
            "wall_s": round(wall_s, 3),
            "fault_spec": fault_spec,
            "frontend": fe_stats,
            "recovery_delta": recovery_delta,
            # per-stage latency percentiles for THIS run (registry delta);
            # the four acceptance stages always appear, observed or not
            "latency": obs.get_registry().delta_summary(
                hist_before, always=("admission_wait", "dispatch", "kernel",
                                     "fallback")),
        }
        if wl is not None:
            report["workload"] = wl.describe()
        report["cache"] = _cache_delta(cache_before)
        if frontend.batcher is not None:
            report["batching"] = frontend.stats().get("batching")
            # the per-slot-attribution invariant: entries that shared a
            # coalesced batch (joined on batch_id, the PR 8 key) must
            # carry ONE degraded verdict — the shared dispatch's. A
            # mixed batch would mean a slot was charged a batch-mate's
            # deadline. Best-effort over the bounded querylog ring.
            by_batch: dict = {}
            for e in obs.querylog.recent():
                if e.get("batch_size", 1) > 1 and "batch_id" in e:
                    by_batch.setdefault(e["batch_id"], set()).add(
                        bool(e.get("degraded")))
            report["batch_mixed_degraded"] = sum(
                1 for flags in by_batch.values() if len(flags) > 1)
            report["batches_observed"] = len(by_batch)
        if errors or deadlocked or untagged_mismatches:
            # invariant breach: this is exactly the moment the flight
            # recorder exists for — the offending requests' span trees are
            # still in the ring. force=True: a breach is never rate-limited
            report["flight_record"] = obs.flight_dump(
                "soak_invariant_breach",
                extra={k: report[k] for k in
                       ("submitted", "served", "shed", "errors",
                        "deadlocked", "untagged_mismatches",
                        "error_samples")},
                out_dir=flight_dir, force=True)
            job.finish(error=f"invariant breach: errors={errors} "
                             f"deadlocked={deadlocked} "
                             f"untagged={untagged_mismatches}")
        else:
            job.finish()
        return report
    except BaseException as e:
        # idempotent finish: the breach/success finishes above win if
        # they already ran; anything escaping earlier (malformed fault
        # spec, frontend init, report assembly) marks the job failed
        # instead of leaving a ghost "running" soak
        job.finish(error=repr(e))
        raise


def run_distributed_soak(index_dir: str, *, shards: int = 2,
                         replicas: int = 2, threads: int = 8,
                         queries: int = 160, seed: int = 0,
                         layout: str = "sparse",
                         worker_deadline_s: float = 1.0,
                         router_config=None,
                         kill_replica_at: float = 0.3,
                         kill_shard_at: float = 0.55,
                         respawn_at: float = 0.75,
                         chaos: bool = True,
                         upgrade_at: float | None = None,
                         upgrade_docs: int = 8,
                         timeout_s: float = 240.0,
                         pacing_s: float = 0.002,
                         rundir: str | None = None,
                         flight_dir: str | None = None,
                         recovery_probes: int = 16,
                         recovery_timeout_s: float = 60.0,
                         workload=None,
                         cache_entries: int | None = None,
                         autoscale=False,
                         scale_plan: dict | None = None) -> dict:
    """The scatter-gather chaos soak (ISSUE 10): mixed traffic through a
    REAL multi-process topology — S doc shards x R replica workers
    behind a Router — while a chaos controller SIGKILLs a replica, then
    a WHOLE shard, then brings everything back. The PR-2 invariants,
    end to end across process boundaries:

    - conservation: shed + served == submitted, zero unstructured
      errors, zero deadlocks;
    - taxonomy: every response is exactly ONE of full / degraded /
      partial (rejections raise Overloaded and count as shed);
    - full responses are BIT-identical to a single-process serial
      reference (docnos, float scores, tie order);
    - partial non-degraded responses are a PINNED-CORRECT subset: equal
      to the exact merge of the healthy shards' ranges, computed from
      an independent full-ranking oracle (not from the workers);
    - with a whole shard dead, partial responses appear
      (partial_fraction > 0) and RECOVERY closes the gap: after
      respawn, a serial probe run must come back all-full.

    Chaos schedule (fractions of completed requests): `kill_replica_at`
    SIGKILLs replica 1 of shard 0 (failover must hide it),
    `kill_shard_at` SIGKILLs every replica of the LAST shard (partial
    results must appear), `respawn_at` restarts all corpses. The
    returned report carries the per-class counts and check results; the
    caller asserts.

    Upgrade-mid-soak (ISSUE 12; `upgrade_at` set, `index_dir` a LIVE
    index): generation B (gen A + `upgrade_docs` synthetic docs) is
    prepared BEFORE the fleet spawns (the swap is what's under test,
    not mid-soak indexing), workers spawn pinned to generation A, and
    at the scheduled fraction a rolling per-replica handoff walks the
    grid. The invariants extend per generation: every response is
    tagged with exactly one generation, full responses are bit-
    identical to THAT generation's serial reference, the mixed window
    is bounded (no old-generation response can complete more than one
    in-flight wave after the roll finishes), and the post-soak recovery
    probes must all serve generation B.

    Elastic membership (ISSUE 16): `scale_plan` scripts deterministic
    scale events into the chaos schedule — `{"up_at": frac}` grows one
    warm replica per shard, `{"down_at": frac}` drains + retires one,
    and `{"kill_during_drain": True}` SIGKILLs the draining replica
    mid-drain (the worst membership race: the drain handshake must
    settle as killed_mid_drain and the router's failover must keep
    conservation). `autoscale=True` (or an AutoscaleConfig) runs the
    closed-loop Autoscaler instead, ticked on its own controller loop
    (a blocking grow must not stall crest recording). Either way the
    report gains a `scale` section (membership epoch, events, drain
    handshakes, mean active replicas, overprovision_fraction) and a
    top-level `burst_p99_ms` — the p99 of served latency during the
    workload's PEAK window (pacing_scale < 1), the number the
    autoscaled-vs-static bench comparison is about. Conservation
    (`shed + served == submitted`) is checked by the SAME breach
    condition across every membership change — that is the contract."""
    from ..index import segments as seg
    from ..obs import get_registry
    from ..search.layout import shard_doc_ranges
    from ..search.scorer import Scorer
    from .router import Router, RouterConfig
    from .shardset import ShardSet

    from .workload import resolve_workload

    if faults.active() is not None:
        raise RuntimeError("a fault plan is already installed")
    if upgrade_at is not None and not seg.is_live(index_dir):
        raise ValueError("upgrade_at needs a LIVE index dir "
                         "(index/segments.py; `tpu-ir ingest --init`)")
    ref_scorer = Scorer.load_generation(index_dir, layout=layout)
    gen_a = ref_scorer.generation
    wl = resolve_workload(ref_scorer, workload, seed=seed)
    reqs = make_queries(ref_scorer, queries, seed=seed, workload=wl)

    # -- generation B: prepared up front, swapped in mid-soak ----------
    gen_b = None
    ref_scorers = {gen_a: ref_scorer}
    if upgrade_at is not None:
        from ..index.ingest import IngestWriter

        rng_u = random.Random(seed * 31 + 7)
        terms = list(ref_scorer.vocab.terms)
        with IngestWriter(index_dir, auto_merge=False) as w:
            for i in range(upgrade_docs):
                w.update(f"UPG-{i:04d}",
                         " ".join(rng_u.choice(terms)
                                  for _ in range(5)))
            w.compact_all(note="upgrade-mid-soak")
        gen_b = seg.LiveIndex.open(index_dir).current_gen()
        ref_scorers[gen_b] = Scorer.load_generation(
            index_dir, gen_b, layout=layout)

    ranges_by_gen = {g: shard_doc_ranges(sc.meta.num_docs, shards)
                     for g, sc in ref_scorers.items()}

    job = obs.start_job(
        "soak", f"routed-soak-{queries}q-{shards}s{replicas}r",
        phases=("reference", "serve", "recovery"),
        config={"threads": threads, "queries": queries, "seed": seed,
                "shards": shards, "replicas": replicas, "chaos": chaos,
                "upgrade_at": upgrade_at})
    try:
        # -- oracles (single-process, before any worker exists):
        # one serial reference + partial-subset oracle PER GENERATION —
        # a response is judged against the corpus snapshot it is tagged
        # with, never across snapshots -------------------------------
        distinct = list({_req_key(r): r for r in reqs}.values())
        obs.report_progress("reference",
                            total=len(distinct) * len(ref_scorers))
        reference: dict = {g: {} for g in ref_scorers}
        full_rank: dict = {g: {} for g in ref_scorers}
        for g, sc in ref_scorers.items():
            oracle_k = min(sc.meta.num_docs, 1000)
            for r in distinct:
                key = _req_key(r)
                res = sc.search_batch(
                    [r["text"]], k=r["k"], scoring=r["scoring"],
                    rerank=r["rerank"])[0]
                if res.degraded:
                    raise RuntimeError("reference run degraded — clear "
                                       "the fault plan before the soak")
                reference[g][key] = list(res)
                if not r["rerank"]:
                    # the independent partial-subset oracle: the FULL
                    # positive ranking by docid, filtered per healthy-
                    # shard set at check time (per-doc scores are
                    # partition-independent, so a filter of the full
                    # ranking IS the healthy shards' exact merge)
                    full_rank[g][key] = list(sc.search_batch(
                        [r["text"]], k=oracle_k, scoring=r["scoring"],
                        return_docids=False)[0])
                obs.report_progress("reference", advance=1)

        reg = get_registry()
        # distributed tracing (ISSUE 18): keep EVERY trace this run and
        # size the store to the request count — the invariant below
        # joins each served response to its stitched waterfall, so the
        # 1-in-N sampling dice and the default 256-trace ring would
        # both make that join racy. reset_all()/process exit restores.
        from ..obs import disttrace
        if disttrace.enabled():
            disttrace.configure(sample=1, max_traces=len(reqs) + 64)
        counters_before = {n: reg.get(n) for n in reg.counter_names()
                           if n.startswith("router.")}
        hist_before = reg.hist_state()
        cache_before = _cache_counters_now()
        obs.report_progress("serve", total=len(reqs))
        results: list = [None] * len(reqs)
        latencies: list = [None] * len(reqs)  # served requests only, ms
        completion_order: list = [0] * len(reqs)
        completed = threading.Event()
        progress = [0]
        progress_lock = threading.Lock()

        with ShardSet(index_dir, shards=shards, replicas=replicas,
                      layout=layout, deadline_s=worker_deadline_s,
                      rundir=rundir,
                      index_generation=(gen_a if upgrade_at is not None
                                        else None)) as shardset:
            # the soak default: a generous per-shard deadline. Dead
            # workers fail at connection-refused speed regardless (the
            # failover/partial paths never wait it out), so a large
            # budget only spares slow-but-alive workers on a contended
            # CI box — it does not slow loss detection.
            cfg_r = router_config or RouterConfig(deadline_ms=3000.0)
            if cache_entries is not None \
                    and cfg_r.cache_entries != cache_entries:
                # an explicit soak-level cache size must not be
                # silently ignored just because a caller also tuned
                # the router knobs (the run_soak coalesce rule)
                from dataclasses import replace as _replace

                cfg_r = _replace(cfg_r, cache_entries=cache_entries)
            router = Router(index_dir, shardset, cfg_r)
            scaler = None
            if autoscale:
                from .autoscale import AutoscaleConfig, Autoscaler

                a_cfg = (autoscale
                         if isinstance(autoscale, AutoscaleConfig) else
                         AutoscaleConfig(
                             min_replicas=replicas,
                             max_replicas=replicas + 1,
                             cooldown_s=0.5,
                             up_occupancy=0.6, down_occupancy=0.15,
                             sustain_up=3, sustain_down=25,
                             drain_timeout_s=15.0))
                # ticked from its own scaler_loop thread at the same
                # 20ms cadence as the chaos controller — tick() blocks
                # through grow() (a full worker spawn), and that block
                # must not stall crest recording or forecaster refits
                scaler = Autoscaler(shardset, router, a_cfg)
            # the predictive arm (ISSUE 19): when the config arms the
            # forecast signal, the controller also drives the telemetry
            # time machine — sampling the occupancy gauge the scaler
            # publishes and refitting the diurnal sinusoid, so
            # forecast_occupancy leads the burst instead of following it
            forecaster = None
            if scaler is not None and scaler.config.forecast_up > 0:
                from ..obs import timeseries

                if timeseries.enabled():
                    # refit at lead/8 (not the live-serving lead/4):
                    # the scripted wave is minutes, not hours, and the
                    # fit must lock inside the first rising edge
                    forecaster = timeseries.Forecaster(
                        timeseries.get_store(),
                        lead_s=scaler.config.forecast_lead_s,
                        sample=True)
                    forecaster.interval_s = max(
                        0.05, forecaster.lead_s / 8.0)
            try:
                # -- chaos + upgrade controller -----------------------
                killed: list = []
                swap_state = {"done_at": None, "result": None}
                swap_complete = threading.Event()
                scale_state: dict = {"drains": [], "samples": [],
                                     "peaks": {}}
                drain_threads: list = []
                # arrival-density crests of the diurnal pacing wave:
                # pacing_scale is minimal (arrivals densest) where the
                # trough-phased wave peaks, i.e. frac = (k + 1/2) / C
                _crest_fracs: list = []
                if wl is not None and getattr(wl, "burst", 0.0) > 0:
                    from .workload import BURST_CYCLES

                    _crest_fracs = [((k + 0.5) / BURST_CYCLES, k)
                                    for k in range(int(BURST_CYCLES))]

                def _retire(s_: int, r_: int) -> None:
                    try:
                        scale_state["drains"].append(
                            shardset.retire_replica(
                                s_, r_, drain_timeout_s=15.0))
                    except Exception:  # noqa: BLE001 — a chaos kill
                        # racing the retire is the scenario, not a crash
                        logger.exception("scale-down retire")

                def _scripted_scale(frac: float, fired: dict) -> None:
                    plan = scale_plan or {}
                    up_at = plan.get("up_at")
                    down_at = plan.get("down_at")
                    if up_at is not None and not fired["scale_up"] \
                            and frac >= up_at:
                        fired["scale_up"] = True

                        def _grow() -> None:
                            try:
                                for s_, r_ in shardset.grow():
                                    # a grown slot may reuse a retired
                                    # index — it must not inherit
                                    # breaker history
                                    router.reset_breaker(s_, r_)
                            except Exception:  # noqa: BLE001
                                logger.exception("scale-up grow")

                        # grow() blocks on a full worker spawn (tens of
                        # seconds) — in a thread, so the controller
                        # keeps ticking and down_at still fires while
                        # traffic is live
                        gth = threading.Thread(target=_grow,
                                               name="soak-grow",
                                               daemon=True)
                        gth.start()
                        drain_threads.append(gth)
                    if down_at is not None and not fired["scale_down"] \
                            and frac >= down_at:
                        fired["scale_down"] = True
                        life = shardset.lifecycle()
                        for s_, states in enumerate(life):
                            active_rs = [r for r, st in enumerate(states)
                                         if st == "active"]
                            if len(active_rs) < 2:
                                continue  # never drain a shard dark
                            r_ = active_rs[-1]
                            if not plan.get("kill_during_drain"):
                                _retire(s_, r_)
                                continue
                            # the worst race, scripted: SIGKILL the
                            # replica WHILE its drain handshake runs
                            th = threading.Thread(
                                target=_retire, args=(s_, r_),
                                name="soak-drain", daemon=True)
                            th.start()
                            drain_threads.append(th)
                            for _ in range(200):
                                if shardset.lifecycle()[s_][r_] \
                                        == "draining":
                                    break
                                time.sleep(0.005)
                            shardset.kill(s_, r_)

                def chaos_controller():
                    fired = {"replica": False, "shard": False,
                             "respawn": False, "upgrade": False,
                             "scale_up": False, "scale_down": False}
                    while not completed.is_set():
                        with progress_lock:
                            frac = progress[0] / max(len(reqs), 1)
                        try:
                            if chaos and not fired["replica"] \
                                    and frac >= kill_replica_at \
                                    and replicas > 1:
                                fired["replica"] = True
                                shardset.kill(0, 1)
                                killed.append((0, 1))
                            if chaos and not fired["shard"] \
                                    and frac >= kill_shard_at:
                                fired["shard"] = True
                                for rr in range(replicas):
                                    if (shards - 1, rr) not in killed:
                                        shardset.kill(shards - 1, rr)
                                        killed.append((shards - 1, rr))
                            if chaos and not fired["respawn"] \
                                    and frac >= respawn_at:
                                fired["respawn"] = True
                                for s_, r_ in list(killed):
                                    shardset.respawn(s_, r_)
                                killed.clear()
                            if upgrade_at is not None \
                                    and not fired["upgrade"] \
                                    and frac >= upgrade_at:
                                fired["upgrade"] = True
                                # the tentpole moment: roll the fleet
                                # onto generation B replica by replica
                                # while traffic keeps flowing
                                from .generation import rolling_swap

                                try:
                                    out = rolling_swap(shardset,
                                                       generation=gen_b)
                                    # the swap driver tells the router
                                    # (ISSUE 15): the result cache's
                                    # key space moves NOW, not when
                                    # traffic happens to reveal gen B —
                                    # a pre-swap head-query entry must
                                    # not stretch the mixed window
                                    router.note_generation(gen_b)
                                    with progress_lock:
                                        swap_state["done_at"] = \
                                            progress[0]
                                    swap_state["result"] = out
                                finally:
                                    # even a failed roll must release
                                    # the held-back traffic tranche
                                    swap_complete.set()
                            if scale_plan:
                                _scripted_scale(frac, fired)
                            if forecaster is not None \
                                    and frac >= _SCALE_WARMUP_FRAC:
                                # fit over post-warmup windows only:
                                # the first requests' JIT warmup spike
                                # is not part of the diurnal wave
                                forecaster.poll()
                            # wall time of each diurnal crest (the
                            # pacing sinusoid peaks at frac (k+1/4)/C)
                            # — the reference the scale-up lead is
                            # measured against
                            for pf, _ in _crest_fracs:
                                if frac >= pf and pf not in \
                                        scale_state["peaks"]:
                                    scale_state["peaks"][pf] = \
                                        time.perf_counter()
                        except Exception:  # noqa: BLE001 — chaos must
                            logger.exception("chaos controller")  # not
                        # the provisioned-vs-demand series behind
                        # mean_replicas / overprovision_fraction
                        scale_state["samples"].append(
                            (shardset.active_replicas(),
                             router.admission.in_flight()))
                        completed.wait(0.02)  # kill the soak itself
                    # whatever is still dead comes back for recovery
                    for s_, r_ in list(killed):
                        try:
                            shardset.respawn(s_, r_)
                        except Exception:  # noqa: BLE001
                            logger.exception("post-soak respawn")

                def scaler_loop():
                    # the autoscaler ticks on its OWN loop: a scale-up
                    # blocks inside grow() for a full worker spawn (tens
                    # of seconds), and that block must not starve the
                    # chaos controller's crest recording or the
                    # forecaster's refits. first_up is stamped at tick
                    # START — the decision instant — not after the grow
                    # returns
                    while not completed.is_set():
                        with progress_lock:
                            frac = progress[0] / max(len(reqs), 1)
                        if frac < _SCALE_WARMUP_FRAC:
                            # the opening requests' JIT warmup inflates
                            # occupancy for a second or two — real
                            # pressure sustains past it, the transient
                            # must not trigger a spurious grow
                            completed.wait(0.02)
                            continue
                        t_dec = time.perf_counter()
                        try:
                            dec = scaler.tick()
                            if dec["action"] == "up" \
                                    and "first_up" not in scale_state:
                                # the A/B's timing datum: when (and on
                                # which signal) growth started
                                scale_state["first_up"] = (
                                    t_dec, dec["reason"], frac)
                        except Exception:  # noqa: BLE001 — a failed
                            logger.exception("scaler tick")  # spawn
                            # must not kill the control loop
                        completed.wait(0.02)

                ctrl = threading.Thread(target=chaos_controller,
                                        name="soak-chaos", daemon=True)
                ctrl.start()
                if scaler is not None:
                    sctl = threading.Thread(target=scaler_loop,
                                            name="soak-scaler",
                                            daemon=True)
                    sctl.start()
                    drain_threads.append(sctl)

                def worker(i: int, r: dict) -> None:
                    if pacing_s:
                        scale = (wl.pacing_scale(i / max(len(reqs), 1))
                                 if wl is not None else 1.0)
                        time.sleep(random.Random(
                            seed * 1_000_003 + i).random()
                            * pacing_s * threads * scale)
                    try:
                        t_req = time.perf_counter()
                        results[i] = ("ok", router.search(
                            r["text"], k=r["k"], scoring=r["scoring"],
                            rerank=r["rerank"]))
                        latencies[i] = (time.perf_counter()
                                        - t_req) * 1e3
                    except Overloaded as e:
                        results[i] = ("shed", e)
                    except BaseException as e:  # structured or nothing
                        results[i] = ("error", e)
                    with progress_lock:
                        progress[0] += 1
                        completion_order[i] = progress[0]
                    job.report("serve", advance=1)

                t0 = time.perf_counter()
                pool = ThreadPoolExecutor(
                    max_workers=threads,
                    thread_name_prefix="routed-soak")
                try:
                    # upgrade-mid-soak: hold the LAST tranche of
                    # requests until the rolling swap confirms, so the
                    # schedule deterministically exercises traffic on
                    # BOTH sides of the handoff no matter how the
                    # soak's wall clock races the reload (workers keep
                    # serving the old generation throughout — nothing
                    # here waits on a dark fleet)
                    hold = 0
                    if upgrade_at is not None:
                        # the held tranche must (a) leave enough
                        # pre-swap traffic for `frac` to actually REACH
                        # upgrade_at (or the trigger dead-stalls until
                        # the wait times out) and (b) never exceed the
                        # request list (worker(-i) would corrupt the
                        # results array)
                        hold = min(max(len(reqs) // 4, threads),
                                   len(reqs) // 2,
                                   int(len(reqs) * (1.0 - upgrade_at)))
                        hold = max(hold, 0)
                    n_pre = len(reqs) - hold
                    futs = [pool.submit(worker, i, r)
                            for i, r in enumerate(reqs[:n_pre])]
                    if hold:
                        swap_complete.wait(min(timeout_s * 0.5, 120.0))
                        futs += [pool.submit(worker, n_pre + j, r)
                                 for j, r in enumerate(reqs[n_pre:])]
                    done, not_done = wait(futs, timeout=timeout_s)
                    for f in not_done:
                        f.cancel()
                finally:
                    completed.set()
                    pool.shutdown(wait=len(results) == len(
                        [o for o in results if o is not None]),
                        cancel_futures=True)
                    ctrl.join(timeout=120.0)
                    for th in drain_threads:
                        # the drain handshake must SETTLE (clean or
                        # killed_mid_drain) before invariants are judged
                        th.join(timeout=60.0)
                wall_s = time.perf_counter() - t0

                # -- recovery probes (topology healthy again) ---------
                # breakers opened during chaos need a success per
                # replica to close, and respawned workers may still be
                # warming: retry each probe briefly instead of judging
                # recovery on the first post-chaos instant
                obs.report_progress("recovery", total=recovery_probes)
                recovery_full = 0
                probe_reqs = reqs[:recovery_probes]
                # after an upgrade the fleet must have CONVERGED: every
                # probe must serve generation B and match ITS reference
                want_gen = gen_b if gen_b is not None else gen_a
                recovery_deadline = (time.monotonic()
                                     + max(recovery_timeout_s, 1.0))
                for r in probe_reqs:
                    while True:
                        try:
                            pres = router.search(r["text"], k=r["k"],
                                                 scoring=r["scoring"],
                                                 rerank=r["rerank"])
                            if Router.classify(pres) == "full" and \
                                    pres.generation == want_gen and \
                                    list(pres) == reference[want_gen][
                                        _req_key(r)]:
                                recovery_full += 1
                                break
                        except Overloaded:
                            pass
                        if time.monotonic() >= recovery_deadline:
                            break
                        time.sleep(0.2)
                    obs.report_progress("recovery", advance=1)
            finally:
                router.close()

        # -- invariant evaluation -------------------------------------
        outcomes = list(results)
        deadlocked = sum(1 for o in outcomes if o is None)
        served = shed = errors = 0
        classes = {"full": 0, "degraded": 0, "partial": 0}
        full_mismatches = partial_mismatches = 0
        partial_checked = tagged_divergent = 0
        hedged_requests = unknown_generation = late_old_generation = 0
        generations_served: dict = {}
        error_reprs: list = []
        swap_done_at = swap_state["done_at"] if upgrade_at is not None \
            else None
        for idx, (out, r) in enumerate(zip(outcomes, reqs)):
            if out is None:
                continue
            state, payload = out
            if state == "shed":
                shed += 1
                continue
            if state == "error":
                errors += 1
                if len(error_reprs) < 5:
                    error_reprs.append(repr(payload))
                continue
            served += 1
            res = payload
            cls = Router.classify(res)
            classes[cls] += 1
            hedged_requests += bool(res.hedges)
            gen = int(getattr(res, "generation", 0))
            generations_served[gen] = generations_served.get(gen, 0) + 1
            if gen not in reference:
                # a response tagged with a generation no oracle knows is
                # an attribution bug, not weather
                unknown_generation += 1
                continue
            if swap_done_at is not None and gen != gen_b \
                    and completion_order[idx] > swap_done_at + threads:
                # the bounded-mixed-window pin: once the rolling swap
                # has confirmed every replica, only the <= `threads`
                # requests already in flight may still answer from the
                # old generation; anything later is an unbounded window
                late_old_generation += 1
            key = _req_key(r)
            if cls == "full":
                if list(res) != reference[gen][key]:
                    full_mismatches += 1
            elif cls == "partial" and not res.degraded \
                    and res.level == "full" and not r["rerank"]:
                # the pinned-correct-subset check: filter the full
                # oracle ranking (of the generation that ANSWERED) to
                # the shards that contributed
                g_ranges = ranges_by_gen[gen]
                ok_ranges = [g_ranges[s] for s in res.shards_ok]
                expect = [(d, s) for d, s in full_rank[gen][key]
                          if any(lo <= d <= hi
                                 for lo, hi in ok_ranges)][: r["k"]]
                mapping = ref_scorers[gen].mapping
                expect = [(mapping.get_docid(int(d)), float(s))
                          for d, s in expect]
                partial_checked += 1
                if list(res) != expect:
                    partial_mismatches += 1
            elif list(res) != reference[gen][key]:
                tagged_divergent += 1

        router_delta = {
            n: reg.get(n) - counters_before.get(n, 0)
            for n in reg.counter_names() if n.startswith("router.")}
        report = {
            "submitted": len(reqs),
            "served": served,
            "shed": shed,
            "errors": errors,
            "error_samples": error_reprs,
            "deadlocked": deadlocked,
            "classes": classes,
            "partial_fraction": round(
                classes["partial"] / max(served, 1), 4),
            "full_mismatches": full_mismatches,
            "partial_checked": partial_checked,
            "partial_mismatches": partial_mismatches,
            "tagged_divergent": tagged_divergent,
            "hedged_requests": hedged_requests,
            "recovery_probes": len(probe_reqs),
            "recovery_full": recovery_full,
            "wall_s": round(wall_s, 3),
            "shards": shards,
            "replicas": replicas,
            "chaos": chaos,
            "generations_served": {str(g): n for g, n in
                                   sorted(generations_served.items())},
            "unknown_generation": unknown_generation,
            "router": router_delta,
            # routed-stage percentiles for THIS run (registry delta):
            # end-to-end routed requests, per-shard worker RTTs, and
            # the host-side exact-merge cost
            "latency": reg.delta_summary(
                hist_before, always=("router.request", "router.shard_rtt",
                                     "router.merge")),
            # the result-cache tier's activity for THIS run (ISSUE 15):
            # hit/miss/evict/stale_generation deltas + hit fraction —
            # the per-skew numbers the bench rows record
            "cache": _cache_delta(cache_before),
        }
        # distributed tracing + SLO (ISSUE 18): the per-response trace
        # join/shape invariant, the run's SLO window state, and the
        # synthetic overhead acceptance (enabled <=5%, disabled <=1% of
        # a mean request) — snapshot BEFORE the overhead bench, whose
        # synthetic slo_record calls would pollute the windows
        if disttrace.enabled():
            report["disttrace"] = _disttrace_eval(outcomes, reqs)
            report["slo"] = disttrace.slo_snapshot()
            served_ms = [v for v in latencies if v is not None]
            report["disttrace"]["overhead"] = _disttrace_overhead(
                sum(served_ms) / len(served_ms) if served_ms else 0.0)
        if wl is not None:
            report["workload"] = wl.describe()
        # burst p99: served latency during the workload's PEAK window
        # (pacing_scale < 1 — arrivals compressed); the whole run when
        # the workload has no burst schedule. This is the number the
        # autoscaled-vs-static comparison trends.
        served_lat = [v for v in latencies if v is not None]
        peak_lat = [latencies[i] for i in range(len(reqs))
                    if latencies[i] is not None and wl is not None
                    and wl.is_peak(i / len(reqs))]
        report["burst_p99_ms"] = _p99_ms(peak_lat or served_lat)
        if autoscale or scale_plan:
            samples = scale_state["samples"]
            wc = max(shardset.max_concurrency, 1)
            over, mean_repl = 0.0, -1.0
            if samples:
                for active, inflight in samples:
                    if active <= 0:
                        continue
                    # replicas the observed in-flight demand did not
                    # need (every request fans out to every shard, so
                    # router in-flight IS per-shard concurrent demand)
                    needed = min(active,
                                 max(1, math.ceil(inflight / wc)))
                    over += (active - needed) / active
                over /= len(samples)
                mean_repl = sum(a for a, _ in samples) / len(samples)
            drains = scale_state["drains"]
            report["scale"] = {
                "events": len(shardset.events()),
                "epoch": shardset.epoch(),
                "lifecycle": shardset.lifecycle(),
                "drains": drains,
                "drained_clean": sum(
                    1 for d in drains if d.get("drained_clean")),
                "killed_mid_drain": sum(
                    1 for d in drains if d.get("killed_mid_drain")),
                "mean_replicas": round(mean_repl, 3),
                "overprovision_fraction": round(over, 4),
                "ticks": len(samples),
            }
            if scaler is not None:
                report["scale"]["autoscaler"] = scaler.snapshot()
            # the A/B timing readout (ISSUE 19): when growth started,
            # on which signal, and how far ahead of the first diurnal
            # crest it landed. forecast_lead_s > 0 means the fleet was
            # growing BEFORE the burst peak; a reactive control fires
            # at/after onset, so its lead hugs zero or goes negative
            if scale_state.get("first_up"):
                t_up, up_reason, up_frac = scale_state["first_up"]
                report["scale"]["first_up_s"] = round(t_up - t0, 3)
                report["scale"]["first_up_reason"] = up_reason
                report["scale"]["first_up_frac"] = round(up_frac, 4)
                peaks = scale_state["peaks"]
                if peaks:
                    first_peak = min(peaks.values())
                    report["scale"]["first_peak_s"] = round(
                        first_peak - t0, 3)
                    report["scale"]["forecast_lead_s"] = round(
                        first_peak - t_up, 3)
        if upgrade_at is not None:
            report["upgrade"] = {
                "generation_a": gen_a,
                "generation_b": gen_b,
                "swap": swap_state["result"],
                "swap_done_at_request": swap_done_at,
                "late_old_generation": late_old_generation,
                "mixed_generation_requests": router_delta.get(
                    "router.mixed_generation", 0),
            }
        breach = (errors or deadlocked or full_mismatches
                  or partial_mismatches or unknown_generation
                  or late_old_generation
                  or served + shed != len(reqs)
                  or report.get("disttrace", {}).get("violations", 0))
        if breach:
            report["flight_record"] = obs.flight_dump(
                "routed_soak_invariant_breach",
                extra={k: report[k] for k in
                       ("submitted", "served", "shed", "errors",
                        "deadlocked", "full_mismatches",
                        "partial_mismatches", "error_samples")},
                out_dir=flight_dir, force=True)
            job.finish(error=f"invariant breach: errors={errors} "
                             f"deadlocked={deadlocked} "
                             f"full_mismatches={full_mismatches} "
                             f"partial_mismatches={partial_mismatches}")
        else:
            job.finish()
        return report
    except BaseException as e:
        job.finish(error=repr(e))
        raise


def _sweep_queries(scorer, n: int, seed: int) -> list[str]:
    """Seeded 1-3 term query texts over the index's own vocabulary —
    one scoring model, no rerank, so every request shares one BatchKey
    and the sweep measures COALESCING, not key fragmentation."""
    rng = random.Random(seed)
    terms = list(scorer.vocab.terms)
    if not terms:
        raise ValueError("scorer has an empty vocabulary")
    return [" ".join(rng.choice(terms)
                     for _ in range(rng.randint(1, 3)))
            for _ in range(n)]


def run_concurrency_sweep(scorer, *, levels=(1, 4, 16),
                          queries_per_level: int = 192, seed: int = 0,
                          k: int = 10, scoring: str = "bm25",
                          coalesce: bool = True,
                          deadline_s: float | None = None,
                          wait_ms: float | None = None) -> dict:
    """The ISSUE 9 acceptance instrument: closed-loop client sweeps at
    each concurrency level through a (by default) coalescing frontend,
    recording batched p50/p95/p99, QPS, the batch-occupancy histogram,
    per-slot coalesce wait, and the compile.recompiles delta per level
    — the numbers that prove concurrent p50 drops below the solo
    dispatch RTT, that occupancy > 1 (coalescing actually engaged), and
    that the precompiled rung ladder holds (zero recompiles).

    Level 1 doubles as the solo-regression guard: its p50 against
    `solo_rtt_ms` (the per-dispatch round trip measured right here,
    same process, same index) bounds what the coalescing wait costs a
    lone caller."""
    reg = obs.get_registry()
    texts = _sweep_queries(scorer, max(queries_per_level, 64), seed)
    # warm EVERY probe query once (1-3 term texts mint distinct pow2
    # analyze widths — an unwarmed width would bill its XLA compile to
    # the RTT), then measure the solo round trip: p50 of 20 post-warm
    # single-query dispatches — the per-dispatch cost a caller pays alone
    for t in texts[:20]:
        scorer.search_batch([t], k=k, scoring=scoring)
    rtts = []
    for t in texts[:20]:
        t0 = time.perf_counter()
        scorer.search_batch([t], k=k, scoring=scoring)
        rtts.append((time.perf_counter() - t0) * 1e3)
    solo_rtt_ms = sorted(rtts)[len(rtts) // 2]

    job = obs.start_job(
        "sweep", f"sweep-{'x'.join(str(n) for n in levels)}",
        phases=("sweep",),
        config={"levels": list(levels), "scoring": scoring, "k": k,
                "coalesce": coalesce, "queries_per_level": queries_per_level})
    out_levels = []
    try:
        obs.report_progress("sweep", total=len(levels) * queries_per_level)
        for level in levels:
            cfg = ServingConfig(
                max_concurrency=int(level),
                max_queue=max(int(level) * 2, 8),
                deadline_s=deadline_s, coalesce=coalesce,
                coalesce_wait_ms=wait_ms)
            frontend = ServingFrontend(scorer, cfg)
            per_client = max(1, queries_per_level // int(level))
            hist_before = reg.hist_state()
            recompiles_before = reg.get("compile.recompiles")
            counters_before = {n: reg.get(n) for n in
                               ("batch.coalesced", "batch.solo_flush")}
            lat_ms: list = []
            shed = errors = 0
            lock = threading.Lock()

            def client(ci: int) -> None:
                nonlocal shed, errors
                rng = random.Random(seed * 7919 + ci)
                local: list = []
                for _ in range(per_client):
                    text = texts[rng.randrange(len(texts))]
                    t0 = time.perf_counter()
                    try:
                        frontend.search(text, k=k, scoring=scoring)
                        local.append((time.perf_counter() - t0) * 1e3)
                    except Overloaded:
                        with lock:
                            shed += 1
                    except Exception:  # noqa: BLE001 — tallied below
                        with lock:
                            errors += 1
                    job.report("sweep", advance=1)
                with lock:
                    lat_ms.extend(local)

            t_start = time.perf_counter()
            pool = ThreadPoolExecutor(max_workers=int(level),
                                      thread_name_prefix="sweep-client")
            try:
                futs = [pool.submit(client, ci) for ci in range(int(level))]
                wait(futs)
            finally:
                pool.shutdown(wait=True)
            wall_s = time.perf_counter() - t_start

            lat_sorted = sorted(lat_ms)

            def pct(p: float) -> float:
                if not lat_sorted:
                    return -1.0
                i = min(len(lat_sorted) - 1,
                        int(round(p / 100.0 * (len(lat_sorted) - 1))))
                return round(lat_sorted[i], 3)

            delta = reg.delta_summary(hist_before,
                                      always=("batch.occupancy",
                                              "batch.wait"))
            row = {
                "concurrency": int(level),
                "served": len(lat_ms),
                "shed": shed,
                "errors": errors,
                "wall_s": round(wall_s, 3),
                "qps": round(len(lat_ms) / wall_s, 1) if wall_s else -1.0,
                "p50_ms": pct(50), "p95_ms": pct(95), "p99_ms": pct(99),
                "occupancy": delta.get("batch.occupancy"),
                "coalesce_wait": delta.get("batch.wait"),
                "coalesced": reg.get("batch.coalesced")
                - counters_before["batch.coalesced"],
                "solo_flush": reg.get("batch.solo_flush")
                - counters_before["batch.solo_flush"],
                "recompiles": reg.get("compile.recompiles")
                - recompiles_before,
            }
            batches = row["coalesced"] + row["solo_flush"]
            # EXACT mean occupancy (served / batches) — the histogram
            # above is log-2-bucketed, good for shape, off by up to one
            # bucket for the single number the sentry trends
            row["occupancy_mean"] = (round(len(lat_ms) / batches, 2)
                                     if batches else -1.0)
            out_levels.append(row)
        job.finish()
    except BaseException as e:
        job.finish(error=repr(e))
        raise
    return {
        "solo_rtt_ms": round(solo_rtt_ms, 3),
        "coalesce": coalesce,
        "scoring": scoring,
        "k": k,
        "queries_per_level": queries_per_level,
        "seed": seed,
        "levels": out_levels,
    }


# ---------------------------------------------------------------------------
# the durable ingest + serve soak (ISSUE 17)
# ---------------------------------------------------------------------------

# deterministic feed vocabulary — overlaps nothing magic; the probe
# queries draw from the same words so every search has matches
_FEED_WORDS = ("harbor lantern orchid tundra velvet quartz meadow "
               "cinder falcon ripple anchor summit juniper marble "
               "ember willow".split())


def _feed_doc(i: int) -> tuple[str, str]:
    """Deterministic document i of the ingest feed — child processes
    and the recovering parent MUST generate identical text for the
    bit-identity check to mean anything."""
    text = " ".join(_FEED_WORDS[(i * 7 + j) % len(_FEED_WORDS)]
                    for j in range(5 + i % 7))
    return f"FEED-{i:06d}", text


def ingest_feed_main(argv=None) -> int:
    """Subprocess entry for the ingest child (soak + the SIGKILL crash
    matrix): open an IngestWriter on `--live-dir`, upsert `_feed_doc(i)`
    for i in [--start, --end), append each docid to `--ack` AFTER the
    writer acknowledged it, flush+compact every `--compact-every` docs.

    Crash realism: an InjectedCrash from the TPU_IR_FAULTS plan is
    converted to a raw SIGKILL of this process — no atexit, no context
    manager unwind, no lease release; exactly what the kernel OOM
    killer leaves behind. Invoked as
    `python -c "from tpu_ir.serving.soak import ingest_feed_main; ingest_feed_main()" ...`.
    """
    import argparse
    import json as _json
    import signal
    import sys

    from ..index.ingest import IngestWriter

    p = argparse.ArgumentParser()
    p.add_argument("--live-dir", required=True)
    p.add_argument("--ack", required=True)
    p.add_argument("--start", type=int, required=True)
    p.add_argument("--end", type=int, required=True)
    p.add_argument("--buffer-docs", type=int, default=8)
    p.add_argument("--compact-every", type=int, default=0)
    p.add_argument("--pause-s", type=float, default=0.0)
    a = p.parse_args(argv if argv is not None else sys.argv[1:])

    ack = open(a.ack, "a", buffering=1)
    try:
        w = IngestWriter(a.live_dir, buffer_docs=a.buffer_docs,
                         auto_merge=False)
        for i in range(a.start, a.end):
            docid, text = _feed_doc(i)
            w.update(docid, text)
            # acknowledge AFTER the writer returned: everything in this
            # file must survive any crash (the WAL holds it)
            ack.write(docid + "\n")
            if a.compact_every and (i + 1 - a.start) % a.compact_every == 0:
                w.flush()
                w.compact_all()
            if a.pause_s:
                time.sleep(a.pause_s)
        w.flush()
        w.compact_all()
        summary = {"acked": a.end - a.start, "replayed": w.replayed,
                   "lease": getattr(w, "lease_info", None),
                   "generation": w.live.current_gen()}
        w.close()
        print(_json.dumps(summary))
        return 0
    except faults.InjectedCrash:
        os.kill(os.getpid(), signal.SIGKILL)
        return 1   # unreachable
    finally:
        ack.close()


def _spawn_feeder(live_dir: str, ack_path: str, start: int, end: int, *,
                  buffer_docs: int = 8, compact_every: int = 0,
                  pause_s: float = 0.0, fault_plan: str | None = None):
    """Popen an ingest_feed_main child (the soak's crashable writer)."""
    import subprocess
    import sys

    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    if fault_plan is not None:
        env["TPU_IR_FAULTS"] = fault_plan
    else:
        env.pop("TPU_IR_FAULTS", None)
    cmd = [sys.executable, "-c",
           "from tpu_ir.serving.soak import ingest_feed_main; "
           "raise SystemExit(ingest_feed_main())",
           "--live-dir", live_dir, "--ack", ack_path,
           "--start", str(start), "--end", str(end),
           "--buffer-docs", str(buffer_docs),
           "--compact-every", str(compact_every),
           "--pause-s", str(pause_s)]
    # child output goes to FILES, not pipes: the parent polls instead of
    # reading, and a filled pipe would wedge the child mid-feed
    out_path = ack_path + f".{start}.out"
    err_path = ack_path + f".{start}.err"
    proc = subprocess.Popen(
        cmd, env=env,
        stdout=open(out_path, "w"), stderr=open(err_path, "w"))
    return proc, out_path, err_path


def _flush_ancestor(live, gen: int) -> dict | None:
    """The nearest ancestor manifest (self included) whose commit
    actually carried mutations (note flush/close) — its `created` stamp
    is when the docs a compacted generation serves became durable, i.e.
    the freshness clock's start."""
    g = gen
    while g is not None:
        try:
            m = live.manifest(g)
        except (OSError, ValueError):
            return None
        if m.get("note") in ("flush", "close"):
            return m
        g = m.get("parent")
    return None


def run_ingest_soak(live_dir: str, *, docs: int = 48, base_docs: int = 12,
                    buffer_docs: int = 6, compact_every: int = 12,
                    kill_fraction: float = 0.5, num_shards: int = 2,
                    probe_threads: int = 2,
                    timeout_s: float = 300.0, seed: int = 0) -> dict:
    """Sustained concurrent ingest + serve, with a mid-soak SIGKILL of
    the ingest process and exactly-once recovery — ROADMAP item 2's
    "make ingest a measured regime", measured under the crash it must
    survive.

    Choreography: the parent seeds `base_docs` and compacts so serving
    can start, then serves the live dir through a ServingFrontend
    (probe threads issuing real queries, reloading onto every new
    servable generation as ingest children land them) while a CHILD
    process feeds `docs` documents through an IngestWriter, flushing +
    compacting every `compact_every`, appending each docid to an ack
    file AFTER the writer acknowledged it. At ~`kill_fraction` of the
    feed the parent SIGKILLs the child mid-stream, then spawns a
    successor that takes over the stale lease, REPLAYS the WAL suffix,
    and resumes from the last acked document (update() upserts make the
    overlap idempotent).

    Asserted invariants (raises AssertionError on breach, with a flight
    record):
    - zero acknowledged-write loss: every acked docid is live in the
      final generation;
    - serving conservation throughout: shed + served + errors ==
      submitted, errors == 0;
    - zero stale responses: no response tagged with a generation older
      than the one adopted before the request started;
    - the successor child actually REPLAYED (the kill landed mid-work).

    Reported: `ingest_docs_per_s` (acked docs over the feeding wall,
    recovery included) and `freshness_lag_ms` (median flush-commit ->
    first-query-served-from-a-generation-containing-it, the
    flush-to-first-servable-query number ROADMAP names) — the two
    bench-check-gated metrics `tpu-ir ingest --soak-bench` records.
    """
    import json as _json
    import signal

    from ..index.ingest import IngestWriter
    from ..index.segments import LiveIndex, is_live, latest_servable
    from ..search.scorer import Scorer
    from .frontend import ServingConfig, ServingFrontend

    job = obs.start_job(
        "ingest-soak", f"ingest-soak-{docs}d",
        phases=("seed", "feed", "recover", "verify"),
        config={"docs": docs, "base_docs": base_docs,
                "compact_every": compact_every,
                "kill_fraction": kill_fraction, "seed": seed})
    reg = obs.get_registry()
    t_start = time.time()
    try:
        obs.report_progress("seed", total=base_docs)
        if not is_live(live_dir):
            # chargram_ks=(): the soak measures durability + freshness,
            # not chargram recall, and word-only builds keep each child
            # compaction cheap enough that the kill lands mid-feed
            LiveIndex.create(live_dir, num_shards=num_shards,
                             chargram_ks=())
        with IngestWriter(live_dir, auto_merge=False) as w:
            existing = w._docs()
            for i in range(base_docs):
                docid, text = _feed_doc(i)
                if docid not in existing:
                    w.update(docid, text)
            w.compact_all(note="ingest-soak base")
        live = LiveIndex.open(live_dir)

        scorer = Scorer.load_generation(live_dir, layout="sparse")
        frontend = ServingFrontend(scorer, ServingConfig(
            max_concurrency=4, max_queue=16))
        served_gen = scorer.generation

        texts = [" ".join(_FEED_WORDS[j % len(_FEED_WORDS)]
                          for j in range(q, q + 2)) for q in range(6)]
        for t in texts:   # warm the probe shapes before the clock runs
            frontend.search(t, k=5, scoring="bm25")

        stop = threading.Event()
        lock = threading.Lock()
        counts = {"submitted": 0, "served": 0, "shed": 0, "errors": 0,
                  "stale": 0}
        gen_seen: set = {served_gen}
        adoptions: list[dict] = []   # {gen, flush_created, first_query}
        adopted = {"gen": served_gen}
        error_samples: list[str] = []

        def probe(tid: int) -> None:
            i = tid
            while not stop.is_set():
                with lock:
                    counts["submitted"] += 1
                    gen_before = adopted["gen"]
                try:
                    res = frontend.search(texts[i % len(texts)], k=5,
                                          scoring="bm25")
                    now = time.time()
                    with lock:
                        counts["served"] += 1
                        gen_seen.add(res.generation)
                        if res.generation < gen_before:
                            # older than the generation published
                            # BEFORE this request started: stale
                            counts["stale"] += 1
                        for a in adoptions:
                            if (a["first_query"] is None
                                    and res.generation >= a["gen"]):
                                a["first_query"] = now
                except Overloaded:
                    with lock:
                        counts["shed"] += 1
                except Exception as e:  # noqa: BLE001 — accounted
                    with lock:
                        counts["errors"] += 1
                        if len(error_samples) < 5:
                            error_samples.append(repr(e))
                i += probe_threads

        def adopt_new_generations() -> None:
            try:
                _path, g = latest_servable(live_dir)
            except (ValueError, OSError):
                return
            if g <= adopted["gen"]:
                return
            flush_m = _flush_ancestor(live, g)
            with lock:
                adoptions.append({
                    "gen": g,
                    "flush_created": (flush_m or {}).get("created"),
                    "first_query": None})
            frontend.reload_generation(generation=g)
            with lock:
                adopted["gen"] = g

        ack_path = os.path.join(live_dir, "ingest-soak.ack")
        open(ack_path, "w").close()

        def acked_now() -> list:
            with open(ack_path, encoding="utf-8") as f:
                return [ln.strip() for ln in f if ln.strip()]

        probes = [threading.Thread(target=probe, args=(t,),
                                   name=f"tpu-ir-ingest-soak-probe-{t}",
                                   daemon=True)
                  for t in range(probe_threads)]
        kills = 0
        child_summary = None
        feed_deadline = time.time() + timeout_s
        t_feed0 = time.time()
        try:
            for th in probes:
                th.start()
            obs.report_progress("feed", total=docs)
            kill_off = max(1, int(docs * kill_fraction))
            # land the kill MID-BUFFER: at an exact flush boundary the
            # WAL suffix is empty and recovery degenerates to a no-op,
            # which is not the regime this soak exists to measure
            if buffer_docs > 1 and kill_off % buffer_docs == 0:
                kill_off += 1
            child, _out1, err1 = _spawn_feeder(
                live_dir, ack_path, base_docs, base_docs + docs,
                buffer_docs=buffer_docs, compact_every=compact_every,
                pause_s=0.02)
            while child.poll() is None:
                # poll much faster than the child feeds (pause_s) so the
                # kill overshoots by at most ~1 doc past kill_off
                if len(acked_now()) >= kill_off and kills == 0:
                    os.kill(child.pid, signal.SIGKILL)
                    child.wait(timeout=30.0)
                    kills += 1
                    break
                if time.time() > feed_deadline:
                    child.kill()
                    raise AssertionError("ingest soak: feeder child "
                                         "exceeded the soak timeout")
                adopt_new_generations()
                time.sleep(0.005)

            obs.report_progress("recover")
            acked_mid = acked_now()
            if kills:
                # resume from the last ACKED doc; the overlap with any
                # in-flight WAL'd doc is idempotent (update upserts)
                resume_from = base_docs + len(acked_mid)
                child2, out2, err2 = _spawn_feeder(
                    live_dir, ack_path, resume_from, base_docs + docs,
                    buffer_docs=buffer_docs,
                    compact_every=compact_every)
                while child2.poll() is None:
                    if time.time() > feed_deadline:
                        child2.kill()
                        raise AssertionError(
                            "ingest soak: recovery child exceeded the "
                            "soak timeout")
                    adopt_new_generations()
                    time.sleep(0.02)
                child2.wait()
                with open(err2, encoding="utf-8") as f:
                    err_text = f.read()
                assert child2.returncode == 0, (
                    f"recovery child failed rc={child2.returncode}: "
                    f"{err_text[-2000:]}")
                with open(out2, encoding="utf-8") as f:
                    child_summary = _json.loads(
                        f.read().strip().splitlines()[-1])
            t_feed1 = time.time()
            # let the probes observe the final generation
            for _ in range(100):
                adopt_new_generations()
                with lock:
                    last = adoptions[-1] if adoptions else None
                if last is None or last["first_query"] is not None:
                    break
                time.sleep(0.02)
        finally:
            stop.set()
            for th in probes:
                th.join(timeout=30.0)

        obs.report_progress("verify")
        acked = acked_now()
        recovered = set(LiveIndex.open(live_dir).live_doc_map())
        lost = [d for d in acked if d not in recovered]
        expected = {_feed_doc(i)[0] for i in range(base_docs + docs)}
        unexpected = sorted(recovered - expected)
        with lock:
            snap = dict(counts)
            adopts = [dict(a) for a in adoptions]
            gens = sorted(gen_seen)

        lags = [(a["first_query"] - a["flush_created"]) * 1e3
                for a in adopts
                if a["first_query"] is not None
                and a["flush_created"] is not None]
        for lag in lags:
            reg.observe("ingest.freshness", lag / 1e3)
        lags.sort()
        freshness_ms = lags[len(lags) // 2] if lags else -1.0
        if lags:
            # the live freshness number (ISSUE 18): /healthz surfaces
            # the run's median flush->first-query lag as a gauge, so an
            # operator reads staleness without digging up a soak report
            reg.set_gauge("ingest.freshness_lag_ms", round(freshness_ms, 3))
        feed_wall = max(t_feed1 - t_feed0, 1e-9)

        report = {
            "docs": docs,
            "base_docs": base_docs,
            "acked": len(acked),
            "recovered_docs": len(recovered),
            "lost_acked": len(lost),
            "unexpected_docs": unexpected[:5],
            "kills": kills,
            "child_replayed": (child_summary or {}).get("replayed"),
            "lease_takeover": bool(((child_summary or {}).get("lease")
                                    or {}).get("taken_over")),
            "feed_wall_s": round(feed_wall, 3),
            "ingest_docs_per_s": round(len(acked) / feed_wall, 2),
            "freshness_lag_ms": round(freshness_ms, 3),
            "freshness_samples": len(lags),
            "swaps": len(adopts),
            "generations_seen": gens,
            **snap,
            "error_samples": error_samples,
            "wall_s": round(time.time() - t_start, 3),
        }
        conserved = (snap["served"] + snap["shed"] + snap["errors"]
                     == snap["submitted"])
        breach = (lost or unexpected or not conserved
                  or snap["errors"] or snap["stale"]
                  or (kills and not (child_summary or {}).get("replayed")))
        if breach:
            report["flight_record"] = obs.flight_dump(
                "ingest_soak_breach",
                extra={k: report[k] for k in
                       ("acked", "lost_acked", "unexpected_docs", "kills",
                        "submitted", "served", "shed", "errors", "stale",
                        "child_replayed", "error_samples")},
                force=True)
            job.finish(error=f"ingest soak breach: lost={len(lost)} "
                             f"stale={snap['stale']} "
                             f"errors={snap['errors']}")
            raise AssertionError(
                f"ingest soak invariant breach: lost_acked={len(lost)} "
                f"unexpected={unexpected[:5]} conserved={conserved} "
                f"errors={snap['errors']} stale={snap['stale']} "
                f"replayed={(child_summary or {}).get('replayed')} "
                f"(flight record: {report['flight_record']})")
        job.finish()
        return report
    except BaseException as e:
        job.finish(error=repr(e))
        raise
