"""Generation-keyed exact-hit result cache (ISSUE 15).

The serving tier's first cache layer: a bounded LRU mapping an EXACT
request identity to its full-level response. The key is

    (normalized terms, k, scoring, rerank, hot_only, generation)

— every field that selects the traced program or the serving route,
PLUS the index generation that would answer a miss. The generation
component is what makes staleness structurally impossible: a live-index
swap (ISSUE 12) bumps the generation, every subsequent lookup key names
the new generation, and every pre-swap entry becomes UNREACHABLE — the
cache is invalidated by key construction, never by a correctness-
critical scan. (`bump_generation` does purge the dead entries, but
that is capacity hygiene + accounting: by the time it runs, no lookup
can reach them.)

Exact-hit only, full-level only: an entry is stored from a non-degraded
non-partial response and replayed verbatim, so a hit is BIT-IDENTICAL
to the miss path — same docids, same float bits, same tie order (the
same contract every prior serving layer carries; the property suite
pins hit == miss across layouts x scorings x rerank). Degraded and
partial responses are transient serving weather and are never frozen
into the cache.

Two deployments share this class:
- the Router's fan-out cache (serving/router.py): a hit skips the
  entire shard fan-out — no RPC, no hedge timer, no shard-RTT sample
  (cache-aware hedging: the trailing-p99 hedge estimate only ever sees
  real worker round trips);
- the ServingFrontend's single-process variant (serving/frontend.py),
  consulted ahead of admission and the coalescer.

Telemetry: cache.hit / cache.miss / cache.evict / cache.stale_generation
counters + the cache.lookup histogram (obs/registry.py), `tpu-ir cache`
(stats / clear), and cache sections on /healthz and /profile.
"""

from __future__ import annotations

import threading
import weakref
from collections import OrderedDict

from ..obs import get_registry

# live caches, weakly referenced — the `tpu-ir cache` CLI and /profile
# enumerate them; registration must not extend an owner's lifetime
_live_caches: list = []
_live_lock = threading.Lock()


def _drop_dead_ref(ref) -> None:
    # weakref finalizer: keep the registry bounded by live owners (a
    # process that churns Routers/frontends must not grow this forever)
    with _live_lock:
        try:
            _live_caches.remove(ref)
        except ValueError:
            pass


def live_caches() -> list:
    """The process's live ResultCache instances (newest last)."""
    with _live_lock:
        alive = []
        for ref in _live_caches:
            c = ref()
            if c is not None:
                alive.append(c)
        return alive


def clear_all() -> int:
    """Drop every live cache's entries (the `tpu-ir cache clear` verb);
    returns the number of entries dropped."""
    return sum(c.clear() for c in live_caches())


def normalize_terms(text: str) -> tuple:
    """The router-side key normalization: whitespace-collapse only.
    The router has no analyzer (workers analyze), so this is the
    strongest normalization that is PROVABLY result-preserving — two
    texts with equal splits are byte-equal modulo whitespace, and the
    workers' analyzer is whitespace-insensitive. Weaker normalization
    than the frontend's analyzed-term-id key costs only missed hits,
    never a wrong one."""
    return tuple(text.split())


def cacheable_text(text: str) -> bool:
    """Texts the exact-hit key covers: no phrase spans (host-scored,
    not routable anyway) and no glob/fuzzy operators — those expand
    against the vocabulary at analyze time, and a normalized key that
    dropped the operator would collide with the literal query."""
    return not any(ch in text for ch in '"*?~')


class ResultCache:
    """Bounded thread-safe LRU of (key -> (generation, payload)).

    `name` labels this instance in stats ("router" / "frontend").
    `capacity` <= 0 disables puts and gets (a convenience so callers
    can construct unconditionally). The payload is opaque to the cache
    (the owners store raw hit tuples + response metadata); `generation`
    rides alongside for the swap-time purge accounting."""

    def __init__(self, capacity: int, *, name: str = "cache"):
        self.name = name
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()
        self._generation = 0
        with _live_lock:
            _live_caches.append(weakref.ref(self, _drop_dead_ref))

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    # -- the key-generation axis -------------------------------------------

    def generation(self) -> int:
        with self._lock:
            return self._generation

    def bump_generation(self, gen: int) -> int:
        """Advance the cache's generation (monotonic — a stale caller
        cannot walk it backwards). Entries keyed to older generations
        are already unreachable (the generation is IN the key); this
        purges them so the bounded capacity serves the new generation,
        and counts them as cache.stale_generation. Returns the number
        purged."""
        purged = 0
        with self._lock:
            if gen <= self._generation:
                return 0
            self._generation = int(gen)
            dead = [k for k, (g, _) in self._entries.items() if g < gen]
            for k in dead:
                del self._entries[k]
            purged = len(dead)
        if purged:
            get_registry().incr("cache.stale_generation", purged)
        return purged

    # -- lookup / store ----------------------------------------------------

    def get(self, key: tuple):
        """The payload for `key`, or None (counts cache.hit/cache.miss;
        a disabled cache counts nothing). Hits refresh LRU order."""
        if not self.enabled:
            return None
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
        reg = get_registry()
        if entry is None:
            reg.incr("cache.miss")
            return None
        reg.incr("cache.hit")
        return entry[1]

    def put(self, key: tuple, payload, *, generation: int) -> None:
        """Store one full-level response payload under its exact key.
        An entry older than the cache's current generation is refused
        (a slow miss completing after a swap must not resurrect the old
        corpus in a fresh slot)."""
        if not self.enabled:
            return
        evicted = 0
        with self._lock:
            if generation < self._generation:
                return
            self._entries[key] = (int(generation), payload)
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                evicted += 1
        if evicted:
            get_registry().incr("cache.evict", evicted)

    def clear(self) -> int:
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
        return n

    # -- introspection -----------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def snapshot(self) -> dict:
        """Control-plane state for /healthz, /profile and `tpu-ir
        cache`: size/capacity/generation, never entry contents (the
        querylog redaction story must hold here too)."""
        with self._lock:
            return {"name": self.name, "capacity": self.capacity,
                    "entries": len(self._entries),
                    "generation": self._generation}


def cache_counters() -> dict:
    """The process-wide cache.* counter view + derived hit fraction
    (`tpu-ir cache stats`, the /profile cache section, soak reports)."""
    from ..obs.registry import CACHE_COUNTER_NAMES

    reg = get_registry()
    out = {name: reg.get(name) for name in CACHE_COUNTER_NAMES}
    looked = out["cache.hit"] + out["cache.miss"]
    out["hit_fraction"] = (round(out["cache.hit"] / looked, 4)
                           if looked else 0.0)
    return out


def resolve_capacity(explicit: int | None) -> int:
    """Capacity resolution shared by RouterConfig / ServingConfig: an
    explicit setting wins; None defers to TPU_IR_CACHE_RESULTS."""
    if explicit is not None:
        return max(int(explicit), 0)
    from ..utils import envvars

    return envvars.get_int("TPU_IR_CACHE_RESULTS")
