"""Circuit breaker around the device dispatch path.

The per-batch deadline (faults.run_with_deadline) bounds ONE request's
latency on a hung device — but with the device permanently down, every
request still pays the full deadline before falling back, and every
deadline burns an abandoned dispatch thread. The breaker makes the
failure diagnosis STICKY:

  closed     normal serving; consecutive device failures are counted.
  open       after `failure_threshold` consecutive failures: requests go
             straight to the host-CPU fallback (force_host) — no device
             dispatch, no deadline wait, no abandoned thread. Steady-state
             latency is the host scorer's, not deadline-per-request.
  half-open  after `cooldown_s` in open, ONE probe request is allowed
             through to the device. Success closes the breaker (full
             service resumes); failure re-opens it for another cooldown.

Counters (opened/probes) feed tpu_ir.utils.report.serving_counters so an
operator can see flapping. Thread-safe; the probe slot is exclusive so a
recovering device sees one probe at a time, not a thundering herd.
"""

from __future__ import annotations

import threading
import time

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    def __init__(self, failure_threshold: int = 5, cooldown_s: float = 1.0,
                 clock=time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self._opened_count = 0
        self._probe_count = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow_device(self) -> tuple[bool, bool]:
        """(allowed, is_probe): may THIS request try the device path,
        and if so, was it admitted as the exclusive half-open probe?
        allowed=False means serve the host fallback directly. The facts
        are returned rather than re-read from `state` afterwards — a
        re-read races other threads' transitions. A request granted the
        probe slot MUST report back via record_success/record_failure,
        or abort() if it died without a device verdict."""
        with self._lock:
            if self._state == CLOSED:
                return True, False
            if self._probe_inflight:
                return False, False
            if (self._state == OPEN
                    and self._clock() - self._opened_at < self.cooldown_s):
                return False, False
            # cooldown elapsed (or already half-open with no probe out):
            # admit exactly one probe
            self._state = HALF_OPEN
            self._probe_inflight = True
            self._probe_count += 1
            return True, True

    def record_success(self, *, is_probe: bool = False) -> None:
        """Report a device success. `is_probe` is the token allow_device
        handed THIS request — verdicts are attributed by token, never by
        re-reading shared state: a stale success from a request admitted
        before the breaker opened must not close it (the device is still
        presumed down until the PROBE says otherwise), and must not
        consume another request's probe slot."""
        with self._lock:
            if is_probe:
                self._probe_inflight = False
                self._consecutive = 0
                self._state = CLOSED
            elif self._state == CLOSED:
                self._consecutive = 0

    def record_failure(self, *, is_probe: bool = False) -> bool:
        """Report a device failure; returns True when THIS call
        transitioned the breaker to open (so the caller can count the
        transition without a racy snapshot sandwich). A probe failure
        always re-opens; a non-probe failure only opens from closed at
        the threshold — stale failures from pre-open requests neither
        consume the probe slot nor push the open timestamp (which would
        starve the next probe)."""
        with self._lock:
            if is_probe:
                self._probe_inflight = False
                opened = self._state != OPEN
                if opened:
                    self._opened_count += 1
                self._state = OPEN
                self._opened_at = self._clock()
                return opened
            self._consecutive += 1
            if (self._state == CLOSED
                    and self._consecutive >= self.failure_threshold):
                self._opened_count += 1
                self._state = OPEN
                self._opened_at = self._clock()
                return True
            return False

    def abort(self, *, is_probe: bool = False) -> None:
        """The admitted request died without a device verdict (an
        exception unrelated to device health — bad query, program bug).
        Leaves failure counts alone; a dying PROBE re-opens the breaker
        and releases its exclusive slot so a later probe can run —
        otherwise the slot would leak and wedge all traffic onto the
        fallback forever."""
        with self._lock:
            if is_probe and self._probe_inflight:
                self._probe_inflight = False
                self._state = OPEN
                self._opened_at = self._clock()

    def reset(self) -> None:
        """Return to pristine CLOSED — the membership-change hook
        (ISSUE 16). When an elastic scale-up reuses a retired slot
        index, the new occupant is a different process on a different
        port: breaker state its predecessor earned (open state,
        consecutive-failure count, an outstanding probe slot) must not
        transfer, or a warm replica would enter the grid already
        half-condemned. In place rather than by discarding the object:
        a request thread that resolved this breaker before the scale
        event must record its verdict where later requests will read
        it. Cumulative telemetry (opened/probe counts) is kept — it
        narrates the slot's history, not the new worker's health."""
        with self._lock:
            self._state = CLOSED
            self._consecutive = 0
            self._probe_inflight = False

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive,
                "opened_count": self._opened_count,
                "probe_count": self._probe_count,
            }
