"""Hot-postings residency hint (ISSUE 15, tentpole d).

A worker's first requests after load pay for lazy device state: the
block-max pre-weighted strips (the deep-k lever from ISSUE 13 — built
per scoring mode on first use) and, on dense layouts, the [V, D+1] tf
matrix. Under Zipf traffic that cost lands exactly where it hurts most:
on the HEAD queries, whose terms are the top-df postings.

This module turns `tpu-ir doctor`'s df-skew report into a load-time
residency decision: when the top-df decile of terms holds most of the
postings mass (the hot strip IS the head of the query distribution —
search/layout.plan_tiers promotes terms by df, so the strip covers the
top-df terms by construction), pre-build the strips / tf matrix at
worker start, before the ready file is written. The first routed
request then finds every head-term structure already device-resident.

TPU_IR_HOT_RESIDENCY: auto (engage when the decile share clears
SKEW_ENGAGE), 1 (force), 0 (off). The hint is a pure warm-up — it
builds exactly the state the first requests would lazily build, so it
can never change a bit of any response.
"""

from __future__ import annotations

import logging
import time

logger = logging.getLogger(__name__)

# auto mode engages when the top-df decile holds at least this share of
# the postings mass — below it, the corpus is flat enough that eager
# residency mostly warms postings uniform traffic rarely revisits
SKEW_ENGAGE = 0.5


def residency_hint(scorer) -> dict:
    """The df-skew signal for THIS scorer's (possibly doc-range-
    restricted) df column — the same computation the doctor reports
    (index/doctor.df_skew_report)."""
    from ..index.doctor import df_skew_report

    return df_skew_report(scorer._df_host())


def prewarm_hot_residency(scorer, *, mode: str | None = None) -> dict:
    """Apply the residency hint to one loaded scorer; returns the
    decision report (/healthz worker identity carries it). Safe to call
    on any layout — it only ever touches state the layout actually
    serves from."""
    from ..utils import envvars

    if mode is None:
        mode = envvars.get_choice("TPU_IR_HOT_RESIDENCY")
    hint = residency_hint(scorer)
    share = hint.get("top_decile_postings_share")
    engage = mode == "1" or (mode == "auto" and share is not None
                             and share >= SKEW_ENGAGE)
    report = {"mode": mode, "engaged": bool(engage), "warmed": [],
              **hint}
    if not engage:
        return report
    t0 = time.perf_counter()
    warmed = report["warmed"]
    if scorer.layout == "sparse":
        # the block-max pre-weighted strips, one per scoring mode (the
        # TF-IDF strip doubles as the cosine rerank's) — each is one
        # device buffer over the hot (= top-df) strip
        for scoring in ("tfidf", "bm25"):
            try:
                if scorer._hot_wstrip(scoring) is not None:
                    warmed.append(f"strip.{scoring}")
            except Exception:  # noqa: BLE001 — a hint must never fail a load
                logger.exception("residency strip warm (%s)", scoring)
        # the per-mode block-max bound tables ride the same hot strip;
        # warm them only when the index carries stored bounds
        if getattr(scorer, "_hot_blk_max", None) is not None:
            for scoring in ("tfidf", "bm25"):
                try:
                    scorer._blockmax_bound_table(scoring)
                    warmed.append(f"bounds.{scoring}")
                except Exception:  # noqa: BLE001
                    logger.exception("residency bounds warm (%s)", scoring)
    elif scorer.layout == "dense":
        # dense BM25 + the explain kernels score from the lazy tf
        # matrix — on this layout it IS the postings residency
        try:
            scorer._ensure_tf_matrix()
            warmed.append("tf_matrix")
        except Exception:  # noqa: BLE001
            logger.exception("residency tf-matrix warm")
    report["warm_s"] = round(time.perf_counter() - t0, 4)
    return report
