"""Elastic capacity: the autoscaler over the ShardSet membership
protocol (ISSUE 16).

PR 15 made "millions of users" traffic a measured regime — diurnal Zipf
waves through the routed soak — but the topology stayed static, so a
burst either overprovisions every trough or trips breakers at every
peak. This module closes the loop: an `Autoscaler` reads SUSTAINED
telemetry the serving tier already emits (router admission occupancy —
executing + queued over capacity) and answers through the ShardSet's
membership protocol:

- **scale up** on sustained pressure: one WARM replica per shard
  (`ShardSet.grow()` — spawn, precompile walk, residency pre-warm, and
  only then enter the dispatch grid, so a burst can never cold-start a
  replica into compile storms that trip its breaker);
- **scale down** on sustained idleness: `ShardSet.retire_replica()` —
  drain-not-drop (the replica leaves the dispatch grid immediately,
  finishes its in-flight requests, then exits; conservation
  `shed + served == submitted` holds across the change).

Two dampers keep the diurnal schedule from making the fleet flap:

- **hysteresis**: a decision needs `sustain_up` / `sustain_down`
  CONSECUTIVE over/under-threshold ticks — a single descheduled poll
  or one hot instant is weather, not a trend (and the up/down
  thresholds are far apart, so the signal can breathe between them
  without triggering either);
- **cooldown**: after any membership change, decisions are suppressed
  for `cooldown_s` (counted as `scale.cooldown_skipped`) — the fleet
  observes the EFFECT of its last action before taking another, which
  bounds the scale-event rate to one per cooldown regardless of how
  violent the wave is.

The control loop runs wherever the caller wants it: `tick()` is one
synchronous decision (deterministic tests, the soak's chaos thread),
`run_in_thread()` owns a daemon poller for live serving. The
`snapshot()` payload rides /healthz (obs/server.register_autoscaler):
membership epoch, per-replica lifecycle, the last decision + reason.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from dataclasses import dataclass

from ..obs import get_registry
from ..utils import envvars

logger = logging.getLogger(__name__)


@dataclass
class AutoscaleConfig:
    """Autoscaler knobs. None defaults defer to the TPU_IR_AUTOSCALE /
    TPU_IR_SCALE_* env registry (RUNBOOK §22)."""

    min_replicas: int | None = None    # per-shard floor (never drained)
    max_replicas: int | None = None    # per-shard ceiling
    cooldown_s: float | None = None    # min seconds between changes
    interval_s: float = 0.05           # thread-mode tick period
    up_occupancy: float = 0.75         # admitted/capacity to arm scale-up
    down_occupancy: float = 0.15       # admitted/capacity to arm drain
    sustain_up: int = 4                # consecutive ticks to scale up
    sustain_down: int = 20             # consecutive ticks to drain
    drain_timeout_s: float = 30.0      # retire's in-flight wait bound
    slo_burn_up: float = 2.0           # fast-window SLO burn to arm
    #                                    scale-up (0 disables the signal)
    forecast_up: float = 0.0           # forecast_occupancy level to arm
    #                                    scale-up (the THIRD signal,
    #                                    ISSUE 19; 0 disables it)
    forecast_lead_s: float | None = None  # forecast horizon; None =
    #                                    TPU_IR_SCALE_LEAD_S

    def resolved(self) -> "AutoscaleConfig":
        from dataclasses import replace

        return replace(
            self,
            min_replicas=(self.min_replicas
                          if self.min_replicas is not None else
                          envvars.get_int("TPU_IR_SCALE_MIN_REPLICAS")),
            max_replicas=(self.max_replicas
                          if self.max_replicas is not None else
                          envvars.get_int("TPU_IR_SCALE_MAX_REPLICAS")),
            cooldown_s=(self.cooldown_s
                        if self.cooldown_s is not None else
                        envvars.get_float("TPU_IR_SCALE_COOLDOWN_S")),
            forecast_lead_s=(self.forecast_lead_s
                             if self.forecast_lead_s is not None else
                             envvars.get_float("TPU_IR_SCALE_LEAD_S")))


def autoscale_enabled(flag: bool | None = None) -> bool:
    """The enablement knob: an explicit flag wins, else
    TPU_IR_AUTOSCALE."""
    return (envvars.get_bool("TPU_IR_AUTOSCALE")
            if flag is None else bool(flag))


class Autoscaler:
    """One control loop over (shardset, router). Thread-safe: `tick()`
    may be driven externally or by the owned poller, and `snapshot()`
    is read concurrently by /healthz."""

    def __init__(self, shardset, router,
                 config: AutoscaleConfig | None = None):
        self.shardset = shardset
        self.router = router
        self.config = (config or AutoscaleConfig()).resolved()
        if self.config.max_replicas < self.config.min_replicas:
            raise ValueError("TPU_IR_SCALE_MAX_REPLICAS < "
                             "TPU_IR_SCALE_MIN_REPLICAS")
        self._lock = threading.Lock()
        self._ticks_over = 0
        self._ticks_under = 0
        self._cooldown_until = 0.0
        self._last_decision = {"action": None, "reason": "never_ticked"}
        self._ticks = 0
        # (active_replicas_min, router in-flight) per tick — the
        # provisioned-vs-needed series overprovision_fraction integrates
        self._samples: list = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        from ..obs.server import register_autoscaler

        register_autoscaler(self)

    # -- the signal --------------------------------------------------------

    def occupancy(self) -> float:
        """The pressure signal: the router's ADMITTED population
        (executing + queued) over its execution capacity. > 1 means
        requests are queueing; ~0 means the fleet is idle. Router-side
        by design: it sees the whole fleet's demand in one number,
        where any single worker's view is one shard's weather."""
        adm = self.router.admission
        return ((adm.in_flight() + adm.queue_depth())
                / max(adm.max_concurrency, 1))

    # -- the decision ------------------------------------------------------

    def tick(self, now: float | None = None) -> dict:
        """One decision instant. Reads the signal, advances the
        hysteresis counters, and (outside cooldown) executes at most
        one membership change. Returns the decision record."""
        cfg = self.config
        now = time.monotonic() if now is None else now
        occ = self.occupancy()
        # the SECOND input signal (ISSUE 18): the SLO tracker's
        # fast-window burn rate. Occupancy sees queue pressure; burn
        # sees requests going bad (slow/partial/errored) even at modest
        # occupancy — either sustained condition arms a scale-up
        from ..obs import disttrace

        burn = disttrace.slo_burn_signal()
        burning = cfg.slo_burn_up > 0 and burn >= cfg.slo_burn_up
        # the THIRD input signal (ISSUE 19): the telemetry time
        # machine's diurnal fit. forecast_occupancy is PREDICTED
        # occupancy forecast_lead_s in the future — arming on it starts
        # growth one lead window before the burst instead of after the
        # queue builds. The gauge is published every-tick current level
        # when the fit fails its quality gate, so a broken forecast
        # degrades to exactly the reactive signal
        reg = get_registry()
        reg.set_gauge("router.occupancy", occ)
        fc = reg.gauges().get("forecast_occupancy", 0.0)
        forecasting = cfg.forecast_up > 0 and fc >= cfg.forecast_up
        active = self.shardset.active_replicas()
        with self._lock:
            self._ticks += 1
            if len(self._samples) < 200_000:
                self._samples.append((active, self.router.admission
                                      .in_flight()))
            if occ >= cfg.up_occupancy or burning or forecasting:
                self._ticks_over += 1
                self._ticks_under = 0
            elif occ <= cfg.down_occupancy:
                self._ticks_under += 1
                self._ticks_over = 0
            else:
                self._ticks_over = 0
                self._ticks_under = 0
            want = None
            if self._ticks_over >= cfg.sustain_up:
                want = "up"
            elif self._ticks_under >= cfg.sustain_down:
                want = "down"
            in_cooldown = now < self._cooldown_until
        decision = {"action": None, "reason": "steady",
                    "occupancy": round(occ, 3), "active": active,
                    "slo_burn": round(burn, 3),
                    "forecast": round(fc, 3), "tick": self._ticks}
        if want == "up":
            if active >= cfg.max_replicas:
                decision["reason"] = "at_max_replicas"
            elif in_cooldown:
                get_registry().incr("scale.cooldown_skipped")
                decision["reason"] = "cooldown"
            else:
                decision.update(self._scale_up(now))
                if decision["action"] == "up" and occ < cfg.up_occupancy:
                    # occupancy alone did not arm this: credit the
                    # predictive signal first, then the burn signal
                    if forecasting and not burning:
                        decision["reason"] = "forecast"
                        get_registry().incr("forecast.scaleups")
                    elif burning:
                        decision["reason"] = "slo_burn"
        elif want == "down":
            if active <= cfg.min_replicas:
                decision["reason"] = "at_min_replicas"
            elif in_cooldown:
                get_registry().incr("scale.cooldown_skipped")
                decision["reason"] = "cooldown"
            else:
                decision.update(self._scale_down(now))
        with self._lock:
            if decision["action"] is not None:
                self._ticks_over = 0
                self._ticks_under = 0
            self._last_decision = decision
        return decision

    def _scale_up(self, now: float) -> dict:
        try:
            added = self.shardset.grow()
        except Exception as e:  # noqa: BLE001 — a failed spawn must not
            # kill the control loop; pressure re-arms the next attempt
            logger.exception("autoscaler scale-up failed")
            return {"action": None, "reason": f"up_failed: {e!r}"}
        # a grown slot may REUSE a retired index: the fresh worker must
        # not inherit the previous occupant's breaker history
        if hasattr(self.router, "reset_breaker"):
            for s, r in added:
                self.router.reset_breaker(s, r)
        with self._lock:
            self._cooldown_until = now + self.config.cooldown_s
        return {"action": "up", "reason": "sustained_pressure",
                "slots": added}

    def _scale_down(self, now: float) -> dict:
        # drain the highest-index active replica of every shard (the
        # symmetric inverse of grow) — chosen under the shardset's own
        # lifecycle view so a concurrent kill can't desync the pick
        life = self.shardset.lifecycle()
        picks = []
        for s, states in enumerate(life):
            active_rs = [r for r, st in enumerate(states)
                         if st == "active"]
            if len(active_rs) > self.config.min_replicas:
                picks.append((s, active_rs[-1]))
        if not picks:
            return {"action": None, "reason": "no_drainable_replica"}
        drains = []
        for s, r in picks:
            try:
                drains.append(self.shardset.retire_replica(
                    s, r, drain_timeout_s=self.config.drain_timeout_s))
            except Exception:  # noqa: BLE001 — a chaos kill racing the
                # pick loses the race benignly; the slot is a corpse
                logger.exception("autoscaler drain failed")
        with self._lock:
            self._cooldown_until = now + self.config.cooldown_s
        return {"action": "down", "reason": "sustained_idleness",
                "drains": drains}

    # -- thread mode -------------------------------------------------------

    def run_in_thread(self) -> "Autoscaler":
        self._thread = threading.Thread(
            target=self._run, name="tpu-ir-autoscaler", daemon=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the loop survives any
                logger.exception("autoscaler tick")  # one bad tick
            self._stop.wait(self.config.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=60.0)
            self._thread = None

    def __enter__(self) -> "Autoscaler":
        return self.run_in_thread()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- accounting / introspection ----------------------------------------

    def utilization_report(self, worker_concurrency: int | None = None
                           ) -> dict:
        """Integrate the tick series into the bench row's two numbers:

        - `mean_replicas`: mean active replicas per shard across ticks
          (the "equal mean replica count" the static control matches);
        - `overprovision_fraction`: mean over ticks of the ACTIVE
          replicas that the observed in-flight load did not need —
          needed(t) = ceil(in_flight(t) / worker max_concurrency),
          clamped to [1, active(t)] (every routed request fans out to
          every shard, so the router's in-flight count IS each shard's
          concurrent demand). 0 = perfectly sized, 0.5 = half the
          fleet idle on average."""
        wc = max(worker_concurrency
                 or getattr(self.shardset, "max_concurrency", 1), 1)
        with self._lock:
            samples = list(self._samples)
        if not samples:
            return {"mean_replicas": -1.0,
                    "overprovision_fraction": -1.0, "ticks": 0}
        over = 0.0
        for active, inflight in samples:
            if active <= 0:
                continue
            needed = min(active, max(1, math.ceil(inflight / wc)))
            over += (active - needed) / active
        return {
            "mean_replicas": round(
                sum(a for a, _ in samples) / len(samples), 3),
            "overprovision_fraction": round(over / len(samples), 4),
            "ticks": len(samples),
        }

    def snapshot(self) -> dict:
        """The /healthz autoscaler section: epoch, per-replica
        lifecycle, hysteresis state, the last decision + reason."""
        cfg = self.config
        with self._lock:
            last = dict(self._last_decision)
            over, under = self._ticks_over, self._ticks_under
            cooldown_left = max(0.0,
                                self._cooldown_until - time.monotonic())
        return {
            "enabled": True,
            "epoch": self.shardset.epoch(),
            "lifecycle": self.shardset.lifecycle(),
            "events": len(self.shardset.events()),
            "occupancy": round(self.occupancy(), 3),
            "ticks_over": over, "ticks_under": under,
            "cooldown_remaining_s": round(cooldown_left, 3),
            "last_decision": last,
            "config": {
                "min_replicas": cfg.min_replicas,
                "max_replicas": cfg.max_replicas,
                "cooldown_s": cfg.cooldown_s,
                "up_occupancy": cfg.up_occupancy,
                "down_occupancy": cfg.down_occupancy,
                "sustain_up": cfg.sustain_up,
                "sustain_down": cfg.sustain_down,
                "slo_burn_up": cfg.slo_burn_up,
                "forecast_up": cfg.forecast_up,
                "forecast_lead_s": cfg.forecast_lead_s,
            },
        }
