"""The TPU_IR_* environment-variable registry: one declaration per knob.

Before ISSUE 6, 15 `TPU_IR_*` env vars were read at 15 ad-hoc
`os.environ.get` sites across nine modules — each with its own parsing,
its own (sometimes absent) validation, and no single place an operator
could ask "what knobs exist?". PR 5's `cache_revalidate_mode()` showed
the right shape for ONE var (validated, fails loudly on a bogus value,
documented); this module generalizes it to all of them:

- every variable is DECLARED here once: name, type, default, allowed
  choices, the RUNBOOK section that documents it, and a one-line
  description;
- typed accessors (`get_str/get_int/get_float/get_bool/get_choice`)
  parse + validate in one place — a malformed value raises a
  `ValueError` naming the variable instead of a bare int() traceback
  (or worse, a silent fall-back to the default); numeric values below a
  declared minimum clamp to it (the pre-registry sites' `max(1, ...)`
  idiom — several accessors run at import time, where raising would
  kill every command before argument parsing);
- `markdown_table()` renders the registry as the RUNBOOK's env-var
  table, so the documentation is GENERATED from the declarations and
  the lint contract pass (tpu_ir/lint/contracts.py, rule TPU302) pins
  the two against drift in either direction;
- the lint pass TPU301 rejects any raw `os.environ` read of a
  `TPU_IR_*` name outside this file, so a new knob cannot ship
  undeclared.

Deliberately dependency-free (os + dataclasses only): the linter loads
this module straight from its file path, keeping `tpu-ir lint` a
pure-CPU, no-JAX command.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

_UNSET = object()


@dataclass(frozen=True)
class EnvVar:
    """One declared knob. `kind` drives parsing/validation; `default` is
    the PARSED default (what accessors return when the var is unset or
    set to the empty string). `runbook` anchors the RUNBOOK section that
    explains the knob — the generated table links it."""

    name: str
    kind: str                 # "str" | "int" | "float" | "bool" | "choice"
    default: object
    description: str
    runbook: str              # RUNBOOK.md section anchor, e.g. "§7"
    choices: tuple = ()       # for kind == "choice"
    # for int/float: values below this are CLAMPED to it, not rejected —
    # the pre-registry read sites clamped (`max(1, ...)`), and several
    # accessors run at module import time, where a raise would take the
    # whole CLI down before argument parsing
    minimum: float | None = None


REGISTRY: dict[str, EnvVar] = {}


def _declare(name: str, kind: str, default, description: str, runbook: str,
             *, choices: tuple = (), minimum: float | None = None) -> None:
    REGISTRY[name] = EnvVar(name, kind, default, description, runbook,
                            choices=choices, minimum=minimum)


# -- the declarations (one line per knob an operator can set) ---------------

_declare("TPU_IR_FAULTS", "str", None,
         "fault-injection plan spec (site[@match]:rule entries, seed=N)",
         "§7")
_declare("TPU_IR_QUARANTINE_KEEP", "int", 8,
         "corrupt artifacts kept in .quarantine/ before eviction", "§7",
         minimum=0)
_declare("TPU_IR_TRACE", "bool", True,
         "0 disables spans AND every latency histogram (one flag test)",
         "§9")
_declare("TPU_IR_TRACE_SAMPLE", "int", 1,
         "keep every N-th root trace in the flight-recorder ring", "§9",
         minimum=1)
_declare("TPU_IR_TRACE_RING", "int", 64,
         "capacity of the recent-traces ring buffer", "§9", minimum=1)
_declare("TPU_IR_JAX_TRACE", "bool", False,
         "1 wraps kernel dispatches in jax.profiler named regions", "§9")
_declare("TPU_IR_FLIGHT_DIR", "str", None,
         "flight-recorder artifact directory (default: system temp)", "§9")
_declare("TPU_IR_FLIGHT_INTERVAL", "float", 30.0,
         "min seconds between two dumps for one reason (rate limit)", "§9",
         minimum=0.0)
_declare("TPU_IR_JOB_HISTORY", "int", 16,
         "finished jobs kept for /jobs (the JobTracker last-K pages)",
         "§10", minimum=1)
_declare("TPU_IR_TELEMETRY_DIR", "str", None,
         "telemetry spool directory enabling cross-process merge", "§10")
_declare("TPU_IR_SPOOL_INTERVAL", "float", 5.0,
         "seconds between background spool refreshes (SpoolWriter)", "§10",
         minimum=0.1)
_declare("TPU_IR_FORMAT_VERSION", "int", 2,
         "artifact format writers emit (1 = npz rollback pin, 2 = arenas)",
         "§12", choices=(1, 2))
_declare("TPU_IR_COMPRESS", "choice", "0",
         "compress part shards at build finalize (bit-packed docids + "
         "quantized tf, format v3): 1 compresses through the "
         "save_with_checksums hook, 0 leaves raw arenas (migrate-index "
         "--compress converts in place either way)", "§26",
         choices=("0", "1"))
_declare("TPU_IR_TF_DTYPE", "choice", "auto",
         "term-frequency quantization for compressed shards: auto "
         "(int8 LUT when lossless, else bf16), int8 (LUT, lossy above "
         "256 distinct values — floor-quantized so blockmax bounds "
         "stay safe), bf16 (always lossless via exception list)", "§26",
         choices=("auto", "int8", "bf16"))
_declare("TPU_IR_LOAD_THREADS", "int", None,
         "concurrent verified shard loads (default min(8, cores))", "§12",
         minimum=1)
_declare("TPU_IR_H2D_CHUNK_BYTES", "int", 64 << 20,
         "host-to-device streaming chunk size in bytes", "§12", minimum=1)
_declare("TPU_IR_CACHE_REVALIDATE", "choice", "stat",
         "serving-cache revalidation: stat (trust size+mtime) or crc "
         "(re-stream and content-prove every hit)", "§12",
         choices=("stat", "crc"))
_declare("TPU_IR_PROFILE", "bool", True,
         "0 disables the jit compile/recompile profiler (one flag test)",
         "§14")
_declare("TPU_IR_PROFILE_COST", "bool", True,
         "0 skips the per-signature cost_analysis probe (FLOPs/bytes)",
         "§14")
_declare("TPU_IR_PROFILE_RECOMPILE_LIMIT", "int", 3,
         "compiles of ONE signature before a recompile-storm flight dump",
         "§14", minimum=1)
_declare("TPU_IR_BENCH_CHECK_WINDOW", "int", 8,
         "trailing comparable BENCH_HISTORY rows the sentry medians over",
         "§14", minimum=1)
_declare("TPU_IR_BENCH_CHECK_MIN_ROWS", "int", 3,
         "comparable prior rows required before bench-check enforces",
         "§14", minimum=1)
_declare("TPU_IR_BENCH_CHECK_TOLERANCE", "float", 0.3,
         "relative degradation vs the window median that breaches "
         "bench-check", "§14", minimum=0.0)
_declare("TPU_IR_BATCH_WAIT_MS", "float", 0.0,
         "max extra ms a promoted batch leader waits to fill toward the "
         "next rung (0 = dispatch immediately; idle solo queries never "
         "wait)", "§16", minimum=0.0)
_declare("TPU_IR_BATCH_LADDER", "str", "1,4,16,64",
         "compiled batch-size rungs the coalescer pads to (bounds "
         "recompilation; largest rung caps batch occupancy). When UNSET, "
         "CPU-class backends drop rungs above 16 — padded rows cost real "
         "compute there; setting the variable overrides the probe", "§16")
_declare("TPU_IR_BATCH_WIDTH", "int", 8,
         "query-width floor (padded term slots) for coalesced batches — "
         "one precompilable width; longer queries bump to their pow2 "
         "bucket (kernel cost scales with width on CPU — keep it near "
         "the real query-length ceiling)", "§16", minimum=1)
_declare("TPU_IR_BATCH_DONATE", "choice", "auto",
         "donate the query-side device buffer on coalesced topk "
         "dispatches: auto (TPU backends only), 1 (force), 0 (off)",
         "§16", choices=("auto", "0", "1"))
_declare("TPU_IR_RADIX_BUCKETS", "int", 16,
         "radix buckets the streaming pass-1 partitions its pair spills "
         "into (0 = legacy per-batch pass-2 combine; >0 turns pass 2 "
         "into per-bucket local device reduces). Default 16: the radix "
         "path is the library default after its PR 11 soak — every "
         "bucket count is fuzz-pinned bit-identical to legacy, so 0 is "
         "a rollback pin, not a safety valve", "§18", minimum=0)
_declare("TPU_IR_TOKENIZE_PROCS", "int", 1,
         "worker processes for the pure-Python tokenizer (1 = in-process;"
         " N>1 analyzes chunks in a pool, byte-identical to serial)",
         "§18", minimum=1)
_declare("TPU_IR_PIPE_DEPTH", "int", 2,
         "build pipeline depth: spill batches / pass-2 buckets the host "
         "prepares ahead of the device (1 = no overlap)", "§18",
         minimum=1)
_declare("TPU_IR_RADIX_PARTS", "bool", False,
         "1 writes bucket-segmented part files straight from the pass-2 "
         "bucket reduces (skips the pass-3 global per-shard sort; parts "
         "are NOT byte-identical to the canonical layout — readers "
         "accept both)", "§18")
_declare("TPU_IR_QUERYLOG", "bool", True,
         "0 disables the sampled query log AND the slow-query trap",
         "§15")
_declare("TPU_IR_QUERYLOG_RING", "int", 256,
         "capacity of the per-process query-log ring", "§15", minimum=1)
_declare("TPU_IR_QUERYLOG_SAMPLE", "int", 1,
         "keep every N-th query entry in the ring (slow queries always "
         "record)", "§15", minimum=1)
_declare("TPU_IR_QUERYLOG_REDACT", "bool", False,
         "1 stores only a stable hash of the analyzed query terms "
         "(privacy: no readable query text in telemetry)", "§15")
_declare("TPU_IR_QUERYLOG_SLOW_KEEP", "int", 16,
         "slow-query captures (span tree + explain) kept in memory",
         "§15", minimum=1)
_declare("TPU_IR_SLOW_QUERY_MS", "float", 0.0,
         "requests at/above this latency are force-captured (explain + "
         "span tree + flight record); 0 disables the trap", "§15",
         minimum=0.0)
_declare("TPU_IR_INGEST_BUFFER_DOCS", "int", 1000,
         "buffered documents that auto-flush the IngestWriter into one "
         "delta segment", "§19", minimum=1)
_declare("TPU_IR_INGEST_KEEP_GENERATIONS", "int", 8,
         "generation manifests gc() keeps; unreferenced segment dirs "
         "are deleted with the manifests that named them", "§19",
         minimum=1)
_declare("TPU_IR_MERGE_FACTOR", "int", 4,
         "segments in one size tier that trigger a tiered merge step "
         "(merge debt threshold)", "§19", minimum=2)
_declare("TPU_IR_MERGE_TIER_RATIO", "float", 8.0,
         "geometric doc-count ratio between merge tiers (each doc is "
         "rewritten about log_ratio(N) times over its lifetime)", "§19",
         minimum=2.0)
_declare("TPU_IR_BLOCKMAX", "choice", "auto",
         "block-max pruning of the tiered hot-strip stage: auto/1 "
         "engage when bounds exist and the doc axis is wide enough, 0 "
         "disables (results are bit-identical either way — the toggle "
         "exists for A/B runs and incident rollback)", "§20",
         choices=("auto", "0", "1"))
_declare("TPU_IR_BLOCKMAX_WIDTH", "int", 512,
         "doc-axis block width for block-max score bounds; fixed per "
         "blockmax.arena artifact at write time (readers use the stored "
         "width). Smaller blocks = tighter bounds but a larger bounds "
         "table and more mask lanes", "§20", minimum=64)
_declare("TPU_IR_BLOCKMAX_STRIP_CACHE", "choice", "auto",
         "device-cache each scoring mode's pre-weighted hot strip "
         "(lntf/saturation of the raw strip — query-independent, yet "
         "recomputed per dispatch in-kernel): auto caches within the "
         "memory budget, 1 forces, 0 disables. Bit-identical either "
         "way; one more strip-sized device buffer per cached mode",
         "§20", choices=("auto", "0", "1"))
_declare("TPU_IR_BLOCKMAX_BLOCKS", "int", 0,
         "doc blocks one block-max dispatch scores exactly (the static "
         "candidate budget); 0 sizes it automatically from k, the block "
         "width and the doc-axis length. Batches whose surviving blocks "
         "overflow the budget fall back to the exact full-width stage "
         "in-kernel (bit-identical, counted as blockmax.fallback)",
         "§20", minimum=0)
_declare("TPU_IR_MERGE_AUTO", "bool", True,
         "0 decouples compaction from flush: IngestWriter stops running "
         "the tiered merge policy inline after every flush — drive "
         "merges explicitly with `tpu-ir compact` (ingest latency stops "
         "paying merge cost; merge debt accumulates until drained)",
         "§19")
_declare("TPU_IR_CACHE_RESULTS", "int", 0,
         "entry capacity of the generation-keyed exact-hit result cache "
         "(router fan-out cache + the serving frontend's single-process "
         "variant); 0 disables both. Hits are bit-identical to the miss "
         "path and invalidate by key on a generation swap", "§21",
         minimum=0)
_declare("TPU_IR_WORKLOAD", "choice", "uniform",
         "traffic shape for soaks and serve-bench: uniform (the legacy "
         "seeded mixed workload) or zipf (rank-skewed term draw over "
         "the index vocabulary — the 'millions of users' shape)", "§21",
         choices=("uniform", "zipf"))
_declare("TPU_IR_WORKLOAD_SKEW", "float", 1.1,
         "Zipf exponent s for --workload zipf: term rank r drawn with "
         "probability proportional to 1/r^s (0 = uniform control; web "
         "query logs measure ~0.7-1.2)", "§21", minimum=0.0)
_declare("TPU_IR_WORKLOAD_BURST", "float", 0.0,
         "diurnal burst amplitude for the workload arrival schedule: 0 "
         "= flat arrivals; b > 0 modulates inter-arrival pacing "
         "sinusoidally so peak-rate traffic runs ~(1+b)x the trough",
         "§21", minimum=0.0)
_declare("TPU_IR_HOT_RESIDENCY", "choice", "auto",
         "pre-warm the hot-postings residency set (block-max strips / "
         "dense tf matrix) at worker load, fed by the doctor's df-skew "
         "report: auto engages when the top-df decile holds most "
         "postings, 1 forces, 0 disables", "§21",
         choices=("auto", "0", "1"))
_declare("TPU_IR_ROUTER_DEADLINE_MS", "float", 500.0,
         "per-shard deadline for one routed request: a shard that "
         "answers on no replica within it ships the response partial",
         "§17", minimum=1.0)
_declare("TPU_IR_ROUTER_HEDGE_MS", "float", 25.0,
         "hedge-delay floor: a second replica is tried once the primary "
         "exceeds max(this, the shard's trailing p99); 0 disables "
         "hedging", "§17", minimum=0.0)
_declare("TPU_IR_ROUTER_CONNECT_MS", "float", 250.0,
         "TCP connect timeout for one shard-worker RPC attempt", "§17",
         minimum=1.0)
_declare("TPU_IR_ROUTER_HEALTH_TTL_S", "float", 2.0,
         "max age of cached per-worker /healthz payloads in the "
         "router's aggregated health view", "§17", minimum=0.0)
_declare("TPU_IR_AUTOSCALE", "bool", False,
         "1 runs the elastic-capacity autoscaler over the shard fleet "
         "(serve-bench --autoscale and embedders): sustained admission "
         "pressure adds a warm replica per shard, sustained idleness "
         "drains one away (drain-not-drop — in-flight requests finish "
         "before the process exits)", "§22")
_declare("TPU_IR_SCALE_MIN_REPLICAS", "int", 1,
         "autoscaler floor: replicas per shard it will never drain "
         "below (the always-on capacity that serves the trough)", "§22",
         minimum=1)
_declare("TPU_IR_SCALE_MAX_REPLICAS", "int", 4,
         "autoscaler ceiling: replicas per shard it will never grow "
         "past (bounds spawn cost and memory under a runaway burst)",
         "§22", minimum=1)
_declare("TPU_IR_SCALE_COOLDOWN_S", "float", 5.0,
         "minimum seconds between autoscaler membership changes: the "
         "flap damper — a diurnal wave shorter than twice this value "
         "cannot make the fleet oscillate (suppressed decisions count "
         "as scale.cooldown_skipped)", "§22", minimum=0.0)
_declare("TPU_IR_WAL", "bool", True,
         "0 disables the ingest write-ahead log AND the writer lease "
         "(durability off: a crash loses every buffered write, and "
         "nothing enforces single-writer) — a rollback pin, not a "
         "tuning knob", "§23")
_declare("TPU_IR_WAL_FSYNC_DOCS", "int", 32,
         "appended WAL records between fsyncs (the Lucene-translog "
         "durability/throughput dial: 1 fsyncs every acknowledged "
         "mutation; a HOST power loss can lose at most one batch — a "
         "process crash loses nothing either way)", "§23", minimum=1)
_declare("TPU_IR_WAL_FSYNC_MS", "float", 50.0,
         "max milliseconds an appended WAL record waits for its batched "
         "fsync (bounds the host-power-loss window in time the way "
         "_FSYNC_DOCS bounds it in records)", "§23", minimum=0.0)
_declare("TPU_IR_WAL_LEASE_TTL_S", "float", 10.0,
         "writer-lease heartbeat TTL: a lease whose heartbeat is older "
         "than this (or whose holder pid is dead) is stale and taken "
         "over on the next writer open; a fresh lease from a live pid "
         "refuses the second writer with WriterLeaseHeld", "§23",
         minimum=0.5)
_declare("TPU_IR_DISTTRACE", "bool", True,
         "0 disables distributed request tracing (traceparent minting, "
         "propagation, span export, stitching) — the per-process span "
         "rings under TPU_IR_TRACE keep working; this kills only the "
         "cross-process layer", "§24")
_declare("TPU_IR_TRACE_TAIL", "bool", True,
         "0 disables tail-keeping: slow/partial/degraded/hedged/error "
         "traces stop being force-kept and fall under the same "
         "1-in-TPU_IR_TRACE_SAMPLE dice as everything else — a "
         "load-shedding pin, not a tuning knob", "§24")
_declare("TPU_IR_SLO_P99_MS", "float", 250.0,
         "the latency SLO: a served request slower than this is a BAD "
         "request for the sliding-window burn-rate tracker (/slo) and "
         "its trace is tail-kept; also the disttrace slow-keep "
         "threshold", "§24", minimum=1.0)
_declare("TPU_IR_TIMESERIES", "bool", True,
         "0 disables the telemetry time machine wholesale — no history "
         "store, no background sampler, /timeseries reports disabled, "
         "the anomaly detector and the forecast signal go dark; the "
         "one-switch rollback for ISSUE 19", "§25")
_declare("TPU_IR_TS_SAMPLE_S", "float", 10.0,
         "seconds between background registry samples: tier-0 window "
         "width, and with the fixed tier factors (x1/x6/x60) the whole "
         "retention ladder — 10 s gives 1 h / 4 h / 24 h", "§25",
         minimum=0.05)
_declare("TPU_IR_TS_ANOMALY_Z", "float", 8.0,
         "robust MAD z-score above which a curated series' newest point "
         "is an anomaly (timeseries.anomaly counter + rate-limited "
         "'anomaly' flight record); 0 disables the detector", "§25",
         minimum=0.0)
_declare("TPU_IR_SCALE_LEAD_S", "float", 30.0,
         "the forecast horizon: the diurnal fit publishes predicted "
         "occupancy this many seconds ahead as forecast_occupancy, so "
         "a forecast-armed autoscaler starts growing one lead window "
         "before the predicted burst", "§25", minimum=0.0)


def _raw(name: str) -> str | None:
    """The raw value, with unset and empty-string both meaning 'use the
    default' (the long-standing `or default` idiom at the old sites)."""
    if name not in REGISTRY:
        raise KeyError(f"undeclared environment variable {name!r}: add it "
                       "to tpu_ir/utils/envvars.py REGISTRY")
    v = os.environ.get(name)
    return v if v else None


def _bad(name: str, value: str, expected: str) -> ValueError:
    return ValueError(f"{name}={value!r}: expected {expected}")


def get_str(name: str, default=_UNSET) -> str | None:
    v = _raw(name)
    if v is None:
        return REGISTRY[name].default if default is _UNSET else default
    return v


def get_int(name: str, default=_UNSET) -> int | None:
    decl = REGISTRY.get(name)
    v = _raw(name)
    if v is None:
        return decl.default if default is _UNSET else default
    try:
        out = int(v)
    except ValueError:
        raise _bad(name, v, "an integer") from None
    if decl.choices and out not in decl.choices:
        raise _bad(name, v, f"one of {decl.choices}")
    if decl.minimum is not None and out < decl.minimum:
        return int(decl.minimum)
    return out


def get_float(name: str, default=_UNSET) -> float | None:
    decl = REGISTRY.get(name)
    v = _raw(name)
    if v is None:
        return decl.default if default is _UNSET else default
    try:
        out = float(v)
    except ValueError:
        raise _bad(name, v, "a number") from None
    if decl.minimum is not None and out < decl.minimum:
        return float(decl.minimum)
    return out


def get_bool(name: str, default=_UNSET) -> bool:
    """The documented 0/1 convention: "0" (exactly) is False for
    default-True flags; any non-empty value is True for default-False
    flags — matching the original `!= "0"` / `== "1"`-ish reads so no
    operator setting changes meaning."""
    decl = REGISTRY.get(name)
    v = _raw(name)
    if v is None:
        return decl.default if default is _UNSET else default
    if decl.default is True:
        return v != "0"
    return v not in ("0", "false", "False")


def get_choice(name: str) -> str:
    """Validated closed-set value (the `cache_revalidate_mode` template):
    case/space-normalized; a value outside the declared choices raises —
    an integrity knob must not fail open to its weaker default."""
    decl = REGISTRY[name]
    v = _raw(name)
    if v is None:
        return decl.default
    out = v.strip().lower()
    if out == "":
        return decl.default
    if out not in decl.choices:
        raise _bad(name, v, f"one of {decl.choices}")
    return out


def is_set(name: str) -> bool:
    """Whether the operator explicitly set the (declared) variable to a
    non-empty value — the hook adaptive defaults use to yield ("auto
    unless overridden": the batch ladder's CPU backend probe must not
    second-guess an explicit TPU_IR_BATCH_LADDER)."""
    return _raw(name) is not None


def declared_names() -> tuple:
    """Every declared TPU_IR_* name, sorted — the contract surface the
    lint pass (TPU301/TPU302) and the RUNBOOK table check against."""
    return tuple(sorted(REGISTRY))


def markdown_table() -> str:
    """The RUNBOOK env-var table, generated from the declarations.
    RUNBOOK §13 embeds this between `<!-- envvar-table -->` markers; the
    lint contract pass re-renders it and fails on any drift, so the
    docs cannot silently rot."""
    rows = ["| variable | type | default | doc | description |",
            "|---|---|---|---|---|"]
    for name in declared_names():
        d = REGISTRY[name]
        if d.kind == "bool":
            default = "1" if d.default else "0"
        elif d.default is None:
            default = "(unset)"
        else:
            default = str(d.default)
        kind = (f"choice{d.choices}" if d.kind == "choice" else d.kind)
        rows.append(f"| `{name}` | {kind} | `{default}` | {d.runbook} | "
                    f"{d.description} |")
    return "\n".join(rows)
