"""Batched device->host transfers.

The TPU-tunnel PJRT transport has a large fixed latency per device->host
fetch (hundreds of ms regardless of size, measured on the axon tunnel), so
sequential `np.asarray` calls on several result arrays serialize that
latency. `fetch_to_host` issues `copy_to_host_async` on every array first so
the copies are in flight together, then materializes them; measured ~2x
faster than sequential fetches for the index-build result set.
"""

from __future__ import annotations

import threading
import time
from functools import partial

import numpy as np


def issue_host_copies(arrays) -> None:
    """Start the async D2H copy of every device array (numpy passes
    through) — THE overlap primitive fetch_to_host and the scorer's
    timed dispatch share, so the in-flight-together discipline has one
    implementation."""
    for a in arrays:
        f = getattr(a, "copy_to_host_async", None)
        if f is not None:
            f()


def fetch_to_host(*arrays) -> list[np.ndarray]:
    """Fetch any number of jax Arrays to host numpy, overlapping the copies.

    Plain numpy arrays pass through unchanged, so callers can mix host and
    device values.
    """
    issue_host_copies(arrays)
    return [np.asarray(a) for a in arrays]


_SLICE_CAST = None


def _slice_cast(a, *, n: int, dtype):
    # the jitted callable is created once so its compilation cache persists
    # across calls (a fresh jax.jit per call would recompile every time —
    # and now also trips the profiler's recompile-storm detector)
    global _SLICE_CAST
    if _SLICE_CAST is None:
        import jax

        from ..obs.profiling import profiled_jit

        @partial(profiled_jit, label="transfer.slice_cast",
                 static_argnames=("n", "dtype"))
        def run(x, *, n, dtype):
            return jax.lax.slice(x, (0,), (n,)).astype(dtype)

        _SLICE_CAST = run
    return _SLICE_CAST(a, n=n, dtype=np.dtype(dtype))


def shrink_for_fetch(a, valid: int, *, dtype=None, granule: int = 1 << 14):
    """Cut a capacity-padded device array down before its D2H copy.

    Device result arrays are padded to a static capacity, but only a
    `valid`-length prefix carries data; fetching the full array wastes
    tunnel bandwidth (the dominant index-build cost on this transport).
    This dispatches a tiny on-device slice-and-cast so only the valid
    prefix — in the narrowest safe dtype — crosses the wire. The slice
    length is bucketed to `granule` so repeat builds reuse one compiled
    program per bucket. Returns the input unchanged when nothing shrinks.
    """
    cap = a.shape[0]
    n = min(cap, max(granule, -(-valid // granule) * granule))
    dt = np.dtype(dtype) if dtype is not None else np.dtype(a.dtype)
    if n == cap and dt == np.dtype(a.dtype):
        return a
    return _slice_cast(a, n=n, dtype=dt)


_SLICE_CAST_ROWS = None
_SLICE_CAST_ROWS_MASKED = None


def _slice_cast_rows(a, *, n: int, dtype):
    global _SLICE_CAST_ROWS
    if _SLICE_CAST_ROWS is None:
        import jax

        from ..obs.profiling import profiled_jit

        @partial(profiled_jit, label="transfer.slice_cast_rows",
                 static_argnames=("n", "dtype"))
        def run(x, *, n, dtype):
            return jax.lax.slice(x, (0, 0),
                                 (x.shape[0], n)).astype(dtype)

        _SLICE_CAST_ROWS = run
    return _SLICE_CAST_ROWS(a, n=n, dtype=np.dtype(dtype))


def _slice_cast_rows_masked(a, valid_rows, *, n: int, dtype):
    # zero every slot past each row's valid count BEFORE the narrowing
    # cast: padding sentinels (PAD_TERM) lie outside uint16 and would
    # otherwise wrap silently. Elementwise ops preserve the leading-axis
    # sharding, so on a mesh this still runs where each shard lives.
    global _SLICE_CAST_ROWS_MASKED
    if _SLICE_CAST_ROWS_MASKED is None:
        import jax
        import jax.numpy as jnp

        from ..obs.profiling import profiled_jit

        @partial(profiled_jit, label="transfer.slice_cast_rows_masked",
                 static_argnames=("n", "dtype"))
        def run(x, rows, *, n, dtype):
            y = jax.lax.slice(x, (0, 0), (x.shape[0], n))
            col = jax.lax.broadcasted_iota(jnp.int32, (1, n), 1)
            return jnp.where(col < rows.astype(jnp.int32)[:, None], y,
                             0).astype(dtype)

        _SLICE_CAST_ROWS_MASKED = run
    return _SLICE_CAST_ROWS_MASKED(a, valid_rows, n=n, dtype=np.dtype(dtype))


def shrink_rows_for_fetch(a, valid: int, *, dtype=None,
                          granule: int = 1 << 14, valid_rows=None):
    """shrink_for_fetch for [S, C] per-shard result arrays: every row
    keeps its first valid-bucket columns (the largest shard's valid
    prefix bounds them all), cast to the narrowest safe dtype. Slicing
    the trailing axis preserves the leading-axis sharding, so on a mesh
    the shrink runs where each shard lives and only real data rides the
    D2H link.

    `valid_rows` (device int [S], each row's own valid count) ENFORCES the
    padding contract: slots past a row's count are zeroed on device before
    the cast, so padding sentinels (PAD_TERM) can never wrap into the
    narrow dtype — a caller that reads past a row's prefix sees zeros, not
    corrupted values (ADVICE r5). Without it the legacy contract applies:
    padding may hold wrapped sentinels and callers MUST slice each row to
    its valid prefix after the fetch."""
    cap = a.shape[1]
    n = min(cap, max(granule, -(-valid // granule) * granule))
    dt = np.dtype(dtype) if dtype is not None else np.dtype(a.dtype)
    if valid_rows is not None:
        return _slice_cast_rows_masked(a, valid_rows, n=n, dtype=dt)
    if n == cap and dt == np.dtype(a.dtype):
        return a
    return _slice_cast_rows(a, n=n, dtype=dt)


_STREAM_UPDATE = None
_STREAM_CHUNK_BYTES = 64 << 20  # default H2D streaming chunk (64 MB)


def _stream_update(buf, chunk, offset):
    """Jitted donated dynamic_update_slice: stitch one uploaded chunk
    into the device buffer in place. Two compiled shapes per (dtype,
    chunk length) — the body chunk and the tail — reused across loads."""
    global _STREAM_UPDATE
    if _STREAM_UPDATE is None:
        import jax

        from ..obs.profiling import profiled_jit

        @partial(profiled_jit, label="transfer.stream_update",
                 donate_argnums=0)
        def run(b, c, o):
            return jax.lax.dynamic_update_slice(b, c, (o,))

        _STREAM_UPDATE = run
    return _STREAM_UPDATE(buf, chunk, offset)


def stream_to_device(a, *, chunk_bytes: int | None = None,
                     expected_crc: str | None = None,
                     label: str | None = None):
    """Chunked host-to-device upload that overlaps disk read, CRC fold
    and transfer (ISSUE 5): the source — typically an np.memmap over a
    v2 arena or serving-cache section — is copied to the device in
    bounded chunks, each `jax.device_put` returning while its transfer
    is in flight so the NEXT chunk's page-in (the disk read) and CRC
    fold run concurrently with it, instead of one monolithic blocking
    device_put serializing read-then-transfer.

    `expected_crc` ('crc32:XXXXXXXX') folds a CRC32 over the bytes as
    they stream and raises faults.IntegrityError on mismatch — verify-
    while-upload, no separate verification pass. Small arrays (<= one
    chunk) take the direct jnp.asarray path.

    Every call is a `load.h2d` span (duration lands in the histogram of
    the same name) and adds its size to the `load.h2d_bytes` counter, so
    effective H2D bandwidth is readable from `tpu-ir metrics` and the
    bench breakdown."""
    import zlib

    import jax
    import jax.numpy as jnp

    from ..obs import get_registry
    from ..obs import trace as obs_trace

    if chunk_bytes is None:
        from . import envvars

        chunk_bytes = envvars.get_int("TPU_IR_H2D_CHUNK_BYTES",
                                      _STREAM_CHUNK_BYTES)
    a = np.asarray(a)
    # dynamic_update_slice offsets are int32 under the default
    # x64-disabled config: past 2**31-1 elements a wrapped offset would
    # CLAMP (not error) and silently overwrite the front of the buffer,
    # so huge arrays take the monolithic path the old code used
    chunkable = a.size <= np.iinfo(np.int32).max
    with obs_trace("load.h2d", bytes=int(a.nbytes),
                   label=label or "<array>"):
        if (a.nbytes <= chunk_bytes or a.ndim == 0 or a.itemsize == 0
                or not chunkable):
            host = np.ascontiguousarray(a)
            if expected_crc is not None:
                _check_crc(zlib.crc32(host.reshape(-1).view(np.uint8)),
                           expected_crc, label)
            out = jnp.asarray(host)
        else:
            flat = np.ascontiguousarray(a).reshape(-1)
            step = max(chunk_bytes // a.itemsize, 1)
            buf = jnp.zeros(flat.shape[0], flat.dtype)
            crc = 0
            for lo in range(0, flat.shape[0], step):
                host_chunk = np.ascontiguousarray(flat[lo : lo + step])
                if expected_crc is not None:
                    crc = zlib.crc32(host_chunk.view(np.uint8), crc)
                dev_chunk = jax.device_put(host_chunk)  # async: in flight
                buf = _stream_update(buf, dev_chunk, np.int32(lo))
            if expected_crc is not None:
                _check_crc(crc, expected_crc, label)
            out = buf.reshape(a.shape)
    get_registry().incr("load.h2d_bytes", int(a.nbytes))
    # memory gauge sample per upload: cold-start HBM growth becomes
    # readable from /metrics and the bench's peak_hbm_bytes
    from ..obs.profiling import sample_memory

    sample_memory()
    return out


def _check_crc(crc: int, expected: str, label: str | None) -> None:
    got = f"crc32:{crc:08x}"
    if got != expected:
        from .. import faults

        raise faults.IntegrityError(
            label or "<array>",
            f"checksum mismatch during device upload (recorded "
            f"{expected}, found {got}); the artifact is corrupt")


def pipeline_depth() -> int:
    """Host-side build pipeline depth (TPU_IR_PIPE_DEPTH, default 2):
    how many items the prefetch side of a producer->device pipeline may
    run ahead of the consumer. 1 disables overlap (strict lockstep)."""
    from . import envvars

    return envvars.get_int("TPU_IR_PIPE_DEPTH")


_PREFETCH_STOP = object()


def prefetch_iter(it, depth: int | None = None, name: str = "prefetch"):
    """Run an iterator on a background thread, `depth` items ahead.

    The double-buffering primitive of the streaming build's
    tokenize->device pipeline (ISSUE 11), generalized from the
    stream_to_device overlap machinery (ISSUE 5): while the consumer —
    typically a device dispatch plus its D2H collection — works on item
    N, the producer thread is already reading/preparing items N+1..N+d.
    numpy file reads and zlib CRC folds release the GIL, so host IO
    genuinely overlaps XLA compute even on the CPU backend.

    Exceptions (BaseException included — an InjectedCrash must propagate
    like a real death) raised by the producer are re-raised in the
    consumer at the point the poisoned item would have been yielded.
    The producer thread is a daemon and is joined on clean exhaustion;
    an abandoned consumer (its own exception) unblocks the producer by
    draining the queue on close."""
    import queue

    if depth is None:
        depth = pipeline_depth()
    if depth <= 1:
        yield from it
        return
    q: queue.Queue = queue.Queue(maxsize=depth)
    # cancellation flag, not just a drain: an abandoned consumer (its
    # own exception mid-build) must STOP the producer, or a tokenizer
    # with hours of corpus left would keep running — parking forever on
    # put() with batch-sized arrays pinned once the one-shot drain below
    # stopped. The producer re-checks the flag on every bounded put.
    stop = threading.Event()

    def produce():
        def put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        try:
            for item in it:
                if not put((None, item)):
                    return
        except BaseException as e:  # re-raised on the consumer side
            put((e, None))
        else:
            put((None, _PREFETCH_STOP))

    t = threading.Thread(target=produce, daemon=True,
                         name=f"tpu-ir-{name}")
    t.start()
    from ..obs import get_registry

    started = False
    try:
        while True:
            if started and q.empty() and t.is_alive():
                # the device outran the host MID-STREAM: the stall
                # counter is the "raise the pipeline depth" signal in
                # tpu-ir stats. The guaranteed-empty wait for the first
                # item and the end-of-stream sentinel are not stalls —
                # counting them would report a 25-50% phantom stall
                # rate on small bucket counts.
                get_registry().incr("build.radix.pipeline_stalls")
            exc, item = q.get()
            if exc is not None:
                raise exc
            if item is _PREFETCH_STOP:
                break
            started = True
            yield item
        t.join()
    finally:
        stop.set()
        # unblock a producer parked in its put wait, then wait for it to
        # actually EXIT: callers (run_pass1_spills) free native state the
        # producer reads (the tokenizer handle) right after closing this
        # generator, so returning while the thread still runs would be a
        # use-after-free. The producer re-checks `stop` every 0.1 s, so
        # this only blocks for the item currently being produced; a
        # warning fires if that item is pathologically slow.
        waited = 0.0
        while t.is_alive():
            try:
                q.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=0.5)
            waited += 0.5
            if waited and waited % 30.0 == 0.0:
                import logging

                logging.getLogger(__name__).warning(
                    "prefetch producer %r still draining after %.0fs "
                    "(slow source read?)", name, waited)


def narrow_uint(max_value: int):
    """Smallest of uint16/int32 that exactly holds values in [0, max_value]."""
    return np.uint16 if max_value < (1 << 16) else np.int32


def shrink_pairs(pair_doc, pair_tf, num_pairs: int, *, num_docs: int,
                 tf_max: int, granule: int = 1 << 18):
    """Shrink the two capacity-padded posting pair columns for fetch.

    Returns the (pair_doc, pair_tf) device arrays sliced to the valid-pair
    bucket and narrowed to the smallest dtypes that hold a docno / tf.
    Callers either async-copy them (deferred fetch) or fetch immediately.
    """
    return (
        shrink_for_fetch(pair_doc, num_pairs, dtype=narrow_uint(num_docs),
                         granule=granule),
        shrink_for_fetch(pair_tf, num_pairs, dtype=narrow_uint(tf_max),
                         granule=granule),
    )
