"""Batched device->host transfers.

The TPU-tunnel PJRT transport has a large fixed latency per device->host
fetch (hundreds of ms regardless of size, measured on the axon tunnel), so
sequential `np.asarray` calls on several result arrays serialize that
latency. `fetch_to_host` issues `copy_to_host_async` on every array first so
the copies are in flight together, then materializes them; measured ~2x
faster than sequential fetches for the index-build result set.
"""

from __future__ import annotations

import numpy as np


def fetch_to_host(*arrays) -> list[np.ndarray]:
    """Fetch any number of jax Arrays to host numpy, overlapping the copies.

    Plain numpy arrays pass through unchanged, so callers can mix host and
    device values.
    """
    for a in arrays:
        f = getattr(a, "copy_to_host_async", None)
        if f is not None:
            f()
    return [np.asarray(a) for a in arrays]
