"""Batched device->host transfers.

The TPU-tunnel PJRT transport has a large fixed latency per device->host
fetch (hundreds of ms regardless of size, measured on the axon tunnel), so
sequential `np.asarray` calls on several result arrays serialize that
latency. `fetch_to_host` issues `copy_to_host_async` on every array first so
the copies are in flight together, then materializes them; measured ~2x
faster than sequential fetches for the index-build result set.
"""

from __future__ import annotations

from functools import partial

import numpy as np


def fetch_to_host(*arrays) -> list[np.ndarray]:
    """Fetch any number of jax Arrays to host numpy, overlapping the copies.

    Plain numpy arrays pass through unchanged, so callers can mix host and
    device values.
    """
    for a in arrays:
        f = getattr(a, "copy_to_host_async", None)
        if f is not None:
            f()
    return [np.asarray(a) for a in arrays]


_SLICE_CAST = None


def _slice_cast(a, *, n: int, dtype):
    # the jitted callable is created once so its compilation cache persists
    # across calls (a fresh jax.jit per call would recompile every time)
    global _SLICE_CAST
    if _SLICE_CAST is None:
        import jax

        @partial(jax.jit, static_argnames=("n", "dtype"))
        def run(x, *, n, dtype):
            return jax.lax.slice(x, (0,), (n,)).astype(dtype)

        _SLICE_CAST = run
    return _SLICE_CAST(a, n=n, dtype=np.dtype(dtype))


def shrink_for_fetch(a, valid: int, *, dtype=None, granule: int = 1 << 14):
    """Cut a capacity-padded device array down before its D2H copy.

    Device result arrays are padded to a static capacity, but only a
    `valid`-length prefix carries data; fetching the full array wastes
    tunnel bandwidth (the dominant index-build cost on this transport).
    This dispatches a tiny on-device slice-and-cast so only the valid
    prefix — in the narrowest safe dtype — crosses the wire. The slice
    length is bucketed to `granule` so repeat builds reuse one compiled
    program per bucket. Returns the input unchanged when nothing shrinks.
    """
    cap = a.shape[0]
    n = min(cap, max(granule, -(-valid // granule) * granule))
    dt = np.dtype(dtype) if dtype is not None else np.dtype(a.dtype)
    if n == cap and dt == np.dtype(a.dtype):
        return a
    return _slice_cast(a, n=n, dtype=dt)


_SLICE_CAST_ROWS = None
_SLICE_CAST_ROWS_MASKED = None


def _slice_cast_rows(a, *, n: int, dtype):
    global _SLICE_CAST_ROWS
    if _SLICE_CAST_ROWS is None:
        import jax

        @partial(jax.jit, static_argnames=("n", "dtype"))
        def run(x, *, n, dtype):
            return jax.lax.slice(x, (0, 0),
                                 (x.shape[0], n)).astype(dtype)

        _SLICE_CAST_ROWS = run
    return _SLICE_CAST_ROWS(a, n=n, dtype=np.dtype(dtype))


def _slice_cast_rows_masked(a, valid_rows, *, n: int, dtype):
    # zero every slot past each row's valid count BEFORE the narrowing
    # cast: padding sentinels (PAD_TERM) lie outside uint16 and would
    # otherwise wrap silently. Elementwise ops preserve the leading-axis
    # sharding, so on a mesh this still runs where each shard lives.
    global _SLICE_CAST_ROWS_MASKED
    if _SLICE_CAST_ROWS_MASKED is None:
        import jax
        import jax.numpy as jnp

        @partial(jax.jit, static_argnames=("n", "dtype"))
        def run(x, rows, *, n, dtype):
            y = jax.lax.slice(x, (0, 0), (x.shape[0], n))
            col = jax.lax.broadcasted_iota(jnp.int32, (1, n), 1)
            return jnp.where(col < rows.astype(jnp.int32)[:, None], y,
                             0).astype(dtype)

        _SLICE_CAST_ROWS_MASKED = run
    return _SLICE_CAST_ROWS_MASKED(a, valid_rows, n=n, dtype=np.dtype(dtype))


def shrink_rows_for_fetch(a, valid: int, *, dtype=None,
                          granule: int = 1 << 14, valid_rows=None):
    """shrink_for_fetch for [S, C] per-shard result arrays: every row
    keeps its first valid-bucket columns (the largest shard's valid
    prefix bounds them all), cast to the narrowest safe dtype. Slicing
    the trailing axis preserves the leading-axis sharding, so on a mesh
    the shrink runs where each shard lives and only real data rides the
    D2H link.

    `valid_rows` (device int [S], each row's own valid count) ENFORCES the
    padding contract: slots past a row's count are zeroed on device before
    the cast, so padding sentinels (PAD_TERM) can never wrap into the
    narrow dtype — a caller that reads past a row's prefix sees zeros, not
    corrupted values (ADVICE r5). Without it the legacy contract applies:
    padding may hold wrapped sentinels and callers MUST slice each row to
    its valid prefix after the fetch."""
    cap = a.shape[1]
    n = min(cap, max(granule, -(-valid // granule) * granule))
    dt = np.dtype(dtype) if dtype is not None else np.dtype(a.dtype)
    if valid_rows is not None:
        return _slice_cast_rows_masked(a, valid_rows, n=n, dtype=dt)
    if n == cap and dt == np.dtype(a.dtype):
        return a
    return _slice_cast_rows(a, n=n, dtype=dt)


def narrow_uint(max_value: int):
    """Smallest of uint16/int32 that exactly holds values in [0, max_value]."""
    return np.uint16 if max_value < (1 << 16) else np.int32


def shrink_pairs(pair_doc, pair_tf, num_pairs: int, *, num_docs: int,
                 tf_max: int, granule: int = 1 << 18):
    """Shrink the two capacity-padded posting pair columns for fetch.

    Returns the (pair_doc, pair_tf) device arrays sliced to the valid-pair
    bucket and narrowed to the smallest dtypes that hold a docno / tf.
    Callers either async-copy them (deferred fetch) or fetch immediately.
    """
    return (
        shrink_for_fetch(pair_doc, num_pairs, dtype=narrow_uint(num_docs),
                         granule=granule),
        shrink_for_fetch(pair_tf, num_pairs, dtype=narrow_uint(tf_max),
                         granule=granule),
    )
