"""Machine-readable job reports.

Replaces the reference's Hadoop counter system + JobTracker pages (SURVEY.md
§5 metrics): each pipeline stage writes one JSON report with the same counter
names the reference exposes (Count.DOCS, Dictionary.Size, map output records,
reduce output groups) plus wall-clock timings per phase.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field

from ..obs.registry import get_registry
from ..obs.trace import trace as _obs_trace


@dataclass
class JobReport:
    job: str
    counters: dict[str, int] = field(default_factory=dict)
    timings_s: dict[str, float] = field(default_factory=dict)
    config: dict = field(default_factory=dict)
    suffix: str = ""  # distinguishes report files of repeated jobs (per-k)
    _t0: float = field(default_factory=time.perf_counter, repr=False)

    def incr(self, name: str, amount: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + amount

    def set_counter(self, name: str, value: int) -> None:
        self.counters[name] = int(value)

    class _Phase:
        def __init__(self, report: "JobReport", name: str):
            self._r, self._name = report, name

        def __enter__(self):
            # every build phase is also a telemetry span: the build-side
            # trace tree + a build.<phase> latency histogram come free
            # for every existing report.phase() call site
            self._span = _obs_trace(f"build.{self._name}")
            self._span.__enter__()
            self._t = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self._r.timings_s[self._name] = self._r.timings_s.get(
                self._name, 0.0) + time.perf_counter() - self._t
            self._span.__exit__(*exc)
            return False

    def phase(self, name: str) -> "JobReport._Phase":
        return JobReport._Phase(self, name)

    def save(self, jobs_dir: str | os.PathLike) -> str:
        os.makedirs(jobs_dir, exist_ok=True)
        out = {
            "job": self.job,
            "wall_s": round(time.perf_counter() - self._t0, 3),
            "counters": self.counters,
            "timings_s": {k: round(v, 3) for k, v in self.timings_s.items()},
            "config": self.config,
            "finished_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
        }
        path = os.path.join(os.fspath(jobs_dir),
                            f"{self.job}{self.suffix}.json")
        with open(path, "w") as f:
            json.dump(out, f, indent=2, sort_keys=True)
        return path


class RecoveryCounters:
    """A named-counter ledger: every retry, degradation, quarantine and
    integrity event increments a counter, so a serving process (or a
    test) can assert that recoveries HAPPENED rather than inferring them
    from silence. Standalone instances remain the per-frontend ledgers
    (tpu_ir.serving.ServingFrontend); the PROCESS-WIDE singletons below
    are now prefix views over the unified TelemetryRegistry
    (tpu_ir.obs) — same surface, one scrape point."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, int] = {}

    def incr(self, name: str, amount: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + amount

    def get(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def snapshot(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()


class _RegistryCounters(RecoveryCounters):
    """RecoveryCounters-compatible view over one TelemetryRegistry
    namespace: `incr("retries")` on the "recovery." view is the
    registry's "recovery.retries". The deprecated-alias half of the
    ISSUE 3 unification — recovery_counters()/serving_counters() keep
    their exact shape while the registry becomes the single home."""

    def __init__(self, prefix: str):
        self._prefix = prefix

    def incr(self, name: str, amount: int = 1) -> None:
        get_registry().incr(self._prefix + name, amount)

    def get(self, name: str) -> int:
        return get_registry().get(self._prefix + name)

    def snapshot(self) -> dict[str, int]:
        return get_registry().counters(self._prefix)

    def reset(self) -> None:
        get_registry().reset_counters(self._prefix)


_RECOVERY = _RegistryCounters("recovery.")


def recovery_counters() -> RecoveryCounters:
    """The process-wide recovery counters — a deprecated thin alias for
    the TelemetryRegistry's "recovery." namespace (tpu_ir.obs is the
    primary surface). Counter names in use: retries, retry_exhausted,
    overflow_retries, degraded_batches, deadline_expired, device_loss,
    forced_host_batches, integrity_failures, quarantined,
    quarantine_evicted, spill_integrity_discards."""
    return _RECOVERY


_SERVING = _RegistryCounters("serving.")


def serving_counters() -> RecoveryCounters:
    """The process-wide serving-frontend counters — a deprecated thin
    alias for the TelemetryRegistry's "serving." namespace (these count
    REQUESTS and control-plane transitions, not fault recoveries).
    Incremented by tpu_ir.serving.ServingFrontend; scraped by
    `tpu-ir stats`. Names in use: submitted, served_full,
    served_no_rerank, served_hot_only, served_breaker_host, degraded,
    shed_queue_full, shed_queue_timeout, shed_level, breaker_opened,
    breaker_probes, level_step_down, level_step_up."""
    return _SERVING
