from .report import JobReport

__all__ = ["JobReport"]
