from .report import JobReport
from .transfer import fetch_to_host

__all__ = ["JobReport", "fetch_to_host"]
