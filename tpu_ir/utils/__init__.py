from .report import JobReport, recovery_counters
from .transfer import fetch_to_host

__all__ = ["JobReport", "fetch_to_host", "recovery_counters"]
