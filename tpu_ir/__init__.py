"""tpu_ir — a TPU-native (JAX/XLA/pjit) information-retrieval framework with
the capabilities of the reference MapReduce search engine
(a-to-the-5/Simple-MapReduce-Search-Engine-Information-Retrieval-):
TREC ingestion, tag-aware analysis, term-k-gram inverted indexing,
char-k-gram wildcard indexing, a term dictionary, and batched top-k TF-IDF /
BM25 ranked retrieval — built SPMD-first on jax.sharding meshes instead of
Hadoop MapReduce."""

__version__ = "0.1.0"


def enable_compilation_cache(path: str | None = None) -> None:
    """Persist XLA executables across processes (big win for repeat builds:
    the device group-by/scoring programs compile once per shape ever).
    Called automatically by the index builder and scorer."""
    import os

    import jax

    path = path or os.path.join(
        os.path.expanduser("~"), ".cache", "tpu_ir", "jax_cache")
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
