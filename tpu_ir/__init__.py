"""tpu_ir — a TPU-native (JAX/XLA/pjit) information-retrieval framework with
the capabilities of the reference MapReduce search engine
(a-to-the-5/Simple-MapReduce-Search-Engine-Information-Retrieval-):
TREC ingestion, tag-aware analysis, term-k-gram inverted indexing,
char-k-gram wildcard indexing, a term dictionary, and batched top-k TF-IDF /
BM25 ranked retrieval — built SPMD-first on jax.sharding meshes instead of
Hadoop MapReduce."""

__version__ = "0.1.0"
