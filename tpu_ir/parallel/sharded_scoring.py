"""SPMD batched scoring: docs sharded over the mesh, queries replicated.

Each device scores its doc block (dense [V, D_shard] layout -> gathers +
fused adds on the VPU/MXU), takes a local top-k, then the per-shard
candidates are all_gather'd and reduced to a global top-k — all inside one
jit. This is the standard distributed-top-k pattern: k*S candidates cross
the interconnect instead of D scores.

The reference has no distributed serving at all (its query path is a single
JVM doing disk seeks, SURVEY.md §3.3); this is the piece that makes 10k-query
batches over pod-scale corpora feasible.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .mesh import SHARD_AXIS


def _score_local(q_terms, q_idf, doc_matrix, doc_base, *, k: int):
    """Body under shard_map. q_terms/q_idf [B, L] replicated;
    doc_matrix [1, V, Dblk] this shard's block; doc_base [1] global docno of
    the block's first column."""
    doc_matrix = doc_matrix.reshape(doc_matrix.shape[-2:])
    doc_base = doc_base.reshape(())

    safe_q = jnp.where(q_terms >= 0, q_terms, 0)
    rows = doc_matrix[safe_q]                          # [B, L, Dblk]
    scores = jnp.einsum("bld,bl->bd", rows, q_idf)     # [B, Dblk]

    kk = min(k, scores.shape[-1])
    loc_scores, loc_idx = jax.lax.top_k(scores, kk)
    if kk < k:  # pad so every shard contributes exactly k candidates
        pad = k - kk
        loc_scores = jnp.pad(loc_scores, ((0, 0), (0, pad)),
                             constant_values=-jnp.inf)
        loc_idx = jnp.pad(loc_idx, ((0, 0), (0, pad)))
    loc_docno = loc_idx.astype(jnp.int32) + doc_base

    # gather candidates from every shard and merge
    all_scores = jax.lax.all_gather(loc_scores, SHARD_AXIS)   # [S, B, k]
    all_docnos = jax.lax.all_gather(loc_docno, SHARD_AXIS)
    s, b, _ = all_scores.shape
    flat_scores = jnp.transpose(all_scores, (1, 0, 2)).reshape(b, s * k)
    flat_docnos = jnp.transpose(all_docnos, (1, 0, 2)).reshape(b, s * k)
    top_scores, top_pos = jax.lax.top_k(flat_scores, k)
    top_docnos = jnp.take_along_axis(flat_docnos, top_pos, axis=1)
    matched = top_scores > 0.0
    return (jnp.where(matched, top_scores, 0.0),
            jnp.where(matched, top_docnos, 0))


@partial(jax.jit, static_argnames=("k", "mesh", "compat_int_idf"))
def sharded_tfidf_topk(
    q_terms: jax.Array,      # int32 [B, L]
    doc_blocks: jax.Array,   # f32 [S, V, Dblk] (1+ln tf), doc-sharded
    doc_bases: jax.Array,    # int32 [S] first global docno per block
    df: jax.Array,           # int32 [V] global df (replicated)
    num_docs,                # int32 scalar
    *,
    mesh,
    k: int = 10,
    compat_int_idf: bool = False,
):
    """Returns (scores [B,k], docnos [B,k]); docno 0 = empty slot."""
    if compat_int_idf:
        n = jnp.asarray(num_docs, jnp.int32)
        ratio = (n // jnp.maximum(df, 1)).astype(jnp.float32)
    else:
        ratio = jnp.asarray(num_docs, jnp.float32) / jnp.maximum(
            df.astype(jnp.float32), 1.0)
    idf = jnp.where(df > 0, jnp.log10(jnp.maximum(ratio, 1e-30)), 0.0)
    vocab_size = doc_blocks.shape[1]
    q_valid = (q_terms >= 0) & (q_terms < vocab_size)
    safe_q = jnp.where(q_terms >= 0, q_terms, 0)
    q_idf = jnp.where(q_valid, idf[safe_q], 0.0)

    fn = jax.shard_map(
        partial(_score_local, k=k),
        mesh=mesh,
        in_specs=(P(None, None), P(None, None),
                  P(SHARD_AXIS, None, None), P(SHARD_AXIS)),
        out_specs=(P(None, None), P(None, None)),
        # outputs are replicated by construction (identical all_gather+merge
        # on every device); the static checker cannot infer that
        check_vma=False,
    )
    scores, docnos = fn(q_terms, q_idf, doc_blocks, doc_bases)
    return scores, docnos


def make_doc_blocks(
    pair_term: np.ndarray, pair_doc: np.ndarray, pair_tf: np.ndarray,
    *, vocab_size: int, num_docs: int, num_shards: int,
) -> tuple[np.ndarray, np.ndarray]:
    """Host-side: global CSR postings -> doc-sharded dense (1+ln tf) blocks.

    Docnos 1..N are split into num_shards contiguous blocks of equal padded
    width. Returns (blocks [S, V, Dblk] f32, doc_bases [S] int32)."""
    dblk = -(-num_docs // num_shards)
    blocks = np.zeros((num_shards, vocab_size, dblk), np.float32)
    w = np.where(pair_tf > 0, 1.0 + np.log(np.maximum(pair_tf, 1)), 0.0)
    shard = (pair_doc - 1) // dblk
    col = (pair_doc - 1) % dblk
    ok = (pair_doc >= 1) & (pair_doc <= num_docs) & (pair_term >= 0) \
        & (pair_term < vocab_size)
    np.add.at(blocks, (shard[ok], pair_term[ok], col[ok]), w[ok])
    doc_bases = (np.arange(num_shards, dtype=np.int32) * dblk + 1).astype(np.int32)
    return blocks, doc_bases
