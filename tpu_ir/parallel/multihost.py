"""Multi-host (multi-process) SPMD support.

The reference scaled by adding Hadoop task trackers; tpu-ir scales by adding
hosts to the jax.distributed job. The same shard_map programs run unchanged:
a global mesh over all devices of all hosts, collectives riding ICI within a
slice and DCN across slices — the framework code is host-count-agnostic
(SURVEY.md §4 "host-count-agnostic SPMD code").

Responsibilities handled here:
- process bootstrap (`init_distributed`) wrapping jax.distributed.initialize;
- corpus partitioning across processes (`process_file_slice`): each host
  streams only its slice of the input files, the moral equivalent of HDFS
  locality-aware splits;
- global docno/vocab agreement: each host tokenizes its slice, then the
  docid and term sets are exchanged host-side (allgather over the process
  group via jax.experimental.multihost_utils) so every process holds the
  same sorted global tables before the device build runs.

Single-process calls are no-ops/identities, so the same driver script runs
everywhere.
"""

from __future__ import annotations

import os
from typing import Sequence

import jax
import numpy as np


def init_distributed(coordinator: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> tuple[int, int]:
    """Initialize jax.distributed when running multi-process; returns
    (process_index, process_count). Safe to call single-process (no-op)."""
    if coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS"):
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id)
    return jax.process_index(), jax.process_count()


def process_file_slice(paths: Sequence[str],
                       process_index: int | None = None,
                       process_count: int | None = None) -> list[str]:
    """Deterministic round-robin assignment of corpus files to processes.

    Every process must call with the same (sorted) path list. Files, not
    byte-ranges, are the split unit — the streaming reader handles any file
    size, and TREC corpora ship as many files."""
    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    expanded: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            expanded.extend(
                os.path.join(p, n) for n in sorted(os.listdir(p))
                if os.path.isfile(os.path.join(p, n)))
        else:
            expanded.append(p)
    return [f for i, f in enumerate(expanded) if i % pc == pi]


def build_index_multihost(
    corpus_paths: Sequence[str] | str,
    index_dir: str,
    *,
    k: int = 1,
    chargram_ks: Sequence[int] = (2, 3),
    compute_chargrams: bool = True,
) -> "object":
    """End-to-end multi-host index build over the global device mesh.

    Every process: streams + tokenizes ITS slice of the corpus files, agrees
    on the global docno/vocab tables host-side, feeds its devices' rows of
    the global occurrence array, runs the shared all_to_all build program,
    and writes the part files for its addressable term shards. Process 0
    writes the shared side artifacts. `index_dir` must be a filesystem all
    processes can write (the HDFS-equivalent assumption).

    Single-process, this degenerates to the SPMD build over local devices.
    """
    import jax
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..analysis.native import make_analyzer
    from ..collection import DocnoMapping, Vocab, kgram_terms, read_trec_corpus
    from ..index import format as fmt
    from ..index.builder import build_chargram_artifacts
    from ..ops.postings import PAD_TERM
    from ..utils import JobReport
    from .mesh import SHARD_AXIS, make_mesh
    from .sharded_build import sharded_build_postings

    if isinstance(corpus_paths, (str, os.PathLike)):
        corpus_paths = [corpus_paths]
    pi, pc = jax.process_index(), jax.process_count()
    os.makedirs(index_dir, exist_ok=True)
    report = JobReport("TermKGramDocIndexer", config={
        "k": k, "multihost": True, "process": pi, "process_count": pc})

    # --- map: tokenize my slice ---
    analyzer = make_analyzer()
    my_files = process_file_slice(corpus_paths, pi, pc)
    my_docids: list[str] = []
    my_doc_terms: list[list[str]] = []
    with report.phase("tokenize"):
        for doc in read_trec_corpus(my_files):
            report.incr("Count.DOCS")
            my_docids.append(doc.docid)
            toks = analyzer.analyze(doc.content)
            my_doc_terms.append(kgram_terms(toks, k) if k > 1 else toks)

    # --- agree on global tables ---
    with report.phase("global_tables"):
        global_docids = allgather_strings(my_docids)
        local_uniques = sorted({t for ts in my_doc_terms for t in ts})
        global_terms = allgather_strings(local_uniques)
        mapping = DocnoMapping(global_docids)
        vocab = Vocab(global_terms)
        num_docs = len(mapping)
        v = len(vocab)
        sorted_terms = np.array(global_terms, dtype=np.str_)
        sorted_docids = np.array(global_docids, dtype=np.str_)

    # --- pack my devices' rows of the global [S, C] occurrence array ---
    n_local = jax.local_device_count()
    s = pc * n_local
    mesh = make_mesh(s)
    with report.phase("pack"):
        per_dev_terms: list[np.ndarray] = []
        per_dev_docs: list[np.ndarray] = []
        per_dev_ndocs = np.zeros(n_local, np.int32)
        buckets: list[list[int]] = [[] for _ in range(n_local)]
        for i in range(len(my_docids)):
            buckets[i % n_local].append(i)
        for dev, idxs in enumerate(buckets):
            terms = [t for i in idxs for t in my_doc_terms[i]]
            tid = np.searchsorted(sorted_terms, np.array(terms, np.str_)
                                  ) if terms else np.zeros(0, np.int64)
            dno = np.concatenate([
                np.full(len(my_doc_terms[i]),
                        np.searchsorted(sorted_docids, my_docids[i]) + 1,
                        np.int32)
                for i in idxs]) if idxs else np.zeros(0, np.int32)
            per_dev_terms.append(tid.astype(np.int32))
            per_dev_docs.append(dno)
            per_dev_ndocs[dev] = len(idxs)
        local_max = max((len(a) for a in per_dev_terms), default=1)
        cap = int(multihost_utils.process_allgather(
            np.int64(local_max)).max())
        granule = 1 << 12
        cap = max(granule, (cap + granule - 1) // granule * granule)
        local_t = np.full((n_local, cap), PAD_TERM, np.int32)
        local_d = np.zeros((n_local, cap), np.int32)
        for dev in range(n_local):
            n = len(per_dev_terms[dev])
            local_t[dev, :n] = per_dev_terms[dev]
            local_d[dev, :n] = per_dev_docs[dev]

        sh2 = NamedSharding(mesh, P(SHARD_AXIS, None))
        sh1 = NamedSharding(mesh, P(SHARD_AXIS))
        g_t = jax.make_array_from_process_local_data(sh2, local_t, (s, cap))
        g_d = jax.make_array_from_process_local_data(sh2, local_d, (s, cap))
        g_n = jax.make_array_from_process_local_data(
            sh1, per_dev_ndocs, (s,))

    # --- the shared SPMD build ---
    with report.phase("postings_device"):
        out = sharded_build_postings(
            g_t, g_d, g_n, vocab_size=v, total_docs=num_docs, mesh=mesh)

    # --- write my shards; gather df/doc_len host-side for side artifacts ---
    with report.phase("write_shards"):
        local_df = np.zeros(v, np.int64)
        for sd in out.df.addressable_shards:
            local_df += np.asarray(sd.data).reshape(-1, v).sum(axis=0)
        df = np.asarray(multihost_utils.process_allgather(local_df))
        df = df.reshape(-1, v).sum(axis=0).astype(np.int32)

        local_dl = np.zeros(num_docs + 1, np.int64)
        for dev in range(n_local):
            np.add.at(local_dl, per_dev_docs[dev], 1)
        doc_len = np.asarray(multihost_utils.process_allgather(local_dl))
        doc_len = doc_len.reshape(-1, num_docs + 1).sum(axis=0).astype(np.int32)

        shard_of, offset_of = fmt.shard_local_offsets(df, s)
        num_pairs_rows = {}
        for sd in out.num_pairs.addressable_shards:
            num_pairs_rows[sd.index[0].start] = int(
                np.asarray(sd.data).ravel()[0])
        doc_rows = {sd.index[0].start: np.asarray(sd.data).reshape(-1)
                    for sd in out.pair_doc.addressable_shards}
        tf_rows = {sd.index[0].start: np.asarray(sd.data).reshape(-1)
                   for sd in out.pair_tf.addressable_shards}
        for row, npairs in num_pairs_rows.items():
            tids = np.nonzero(shard_of == row)[0].astype(np.int32)
            lens = df[tids].astype(np.int64)
            local_indptr = np.concatenate([[0], np.cumsum(lens)])
            fmt.save_shard(index_dir, row, term_ids=tids,
                           indptr=local_indptr,
                           pair_doc=doc_rows[row][:npairs],
                           pair_tf=tf_rows[row][:npairs],
                           df=df[tids])

    # --- process 0 writes shared side artifacts ---
    if pi == 0:
        mapping.save(os.path.join(index_dir, fmt.DOCNOS))
        vocab.save(os.path.join(index_dir, fmt.VOCAB))
        np.save(os.path.join(index_dir, fmt.DOCLEN), doc_len)
        # offsets were derived from the global df, so process 0 holds them all
        fmt.write_dictionary(index_dir, vocab.terms, shard_of, offset_of)
        built_chargrams = bool(compute_chargrams and chargram_ks and k == 1)
        if built_chargrams:
            build_chargram_artifacts(index_dir, vocab.terms,
                                     list(chargram_ks))
        meta = fmt.IndexMetadata(
            num_docs=num_docs, vocab_size=v, k=k, num_shards=s,
            num_pairs=int(df.sum()),
            chargram_ks=list(chargram_ks) if built_chargrams else [])
        meta.save(index_dir)
        report.save(os.path.join(index_dir, fmt.JOBS_DIR))
    multihost_utils.sync_global_devices("tpu_ir_index_built")
    return fmt.IndexMetadata.load(index_dir)


def allgather_strings(local: Sequence[str]) -> list[str]:
    """Union of string sets across processes (sorted). Uses host-side
    broadcast through the jax coordination service; single-process = sorted
    unique of the input."""
    if jax.process_count() == 1:
        return sorted(set(local))
    from jax.experimental import multihost_utils

    # encode local strings as a padded uint8 matrix; negotiate the global
    # matrix shape first (hosts have different set sizes), then allgather.
    blobs = [s.encode("utf-8") for s in sorted(set(local))]
    max_len = max((len(b) for b in blobs), default=1)
    dims = multihost_utils.process_allgather(
        np.array([len(blobs), max_len], np.int64))          # [P, 2]
    rows = int(dims[:, 0].max())
    width = int(dims[:, 1].max())
    arr = np.zeros((max(rows, 1), width), np.uint8)
    for i, b in enumerate(blobs):
        arr[i, : len(b)] = np.frombuffer(b, np.uint8)
    gathered = np.asarray(multihost_utils.process_allgather(arr))  # [P, R, W]
    out: set[str] = set()
    for row in gathered.reshape(-1, width):
        b = bytes(row).rstrip(b"\x00")
        if b:
            out.add(b.decode("utf-8"))
    return sorted(out)
