"""Multi-host (multi-process) SPMD support.

The reference scaled by adding Hadoop task trackers; tpu-ir scales by adding
hosts to the jax.distributed job. The same shard_map programs run unchanged:
a global mesh over all devices of all hosts, collectives riding ICI within a
slice and DCN across slices — the framework code is host-count-agnostic
(SURVEY.md §4 "host-count-agnostic SPMD code").

Responsibilities handled here:
- process bootstrap (`init_distributed`) wrapping jax.distributed.initialize;
- corpus partitioning across processes (`process_file_slice`): each host
  streams only its slice of the input files, the moral equivalent of HDFS
  locality-aware splits;
- global docno/vocab agreement: each host tokenizes its slice, then the
  docid and term sets are exchanged host-side (allgather over the process
  group via jax.experimental.multihost_utils) so every process holds the
  same sorted global tables before the device build runs;
- the streaming multi-host build itself (`build_index_multihost`): chunked
  native ingestion + local spills + lockstep per-batch SPMD shuffle steps,
  so no process ever holds its slice's tokens in memory — the composition
  of index/streaming.py's out-of-core passes with the mesh program.

Single-process calls are no-ops/identities, so the same driver script runs
everywhere.
"""

from __future__ import annotations

import os
import re
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.transfer import fetch_to_host, narrow_uint, shrink_rows_for_fetch


def init_distributed(coordinator: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> tuple[int, int]:
    """Initialize jax.distributed when running multi-process; returns
    (process_index, process_count). Safe to call single-process (no-op)."""
    if coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS"):
        # CPU multi-process needs a cross-process collectives backend; the
        # default ("none") hard-fails at the first collective with
        # "Multiprocess computations aren't implemented on the CPU
        # backend". Select gloo when available and nothing was chosen —
        # non-CPU platforms ignore the flag, and jax builds without the
        # flag/gloo keep their previous behavior.
        try:
            # flag-style options are not attribute-readable on every jax
            # version; update() is the portable surface, so only flip the
            # default, never an explicit operator choice (env var)
            if not os.environ.get("JAX_CPU_COLLECTIVES_IMPLEMENTATION"):
                jax.config.update("jax_cpu_collectives_implementation",
                                  "gloo")
        except (AttributeError, ValueError):
            pass
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id)
    return jax.process_index(), jax.process_count()


def process_file_slice(paths: Sequence[str],
                       process_index: int | None = None,
                       process_count: int | None = None) -> list[str]:
    """Deterministic round-robin assignment of corpus files to processes.

    Every process must call with the same (sorted) path list. Files, not
    byte-ranges, are the split unit — the streaming reader handles any file
    size, and TREC corpora ship as many files."""
    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    expanded: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            expanded.extend(
                os.path.join(p, n) for n in sorted(os.listdir(p))
                if os.path.isfile(os.path.join(p, n)))
        else:
            expanded.append(p)
    return [f for i, f in enumerate(expanded) if i % pc == pi]


def build_index_multihost(corpus_paths, index_dir, **kwargs) -> "object":
    """The public multi-host build, run as a tracked job (each process
    tracks its OWN slice's progress; /jobs on any process shows that
    process's passes — the cluster view is the aggregate module's job).
    On completion, the process spools its telemetry snapshot when
    TPU_IR_TELEMETRY_DIR is set, so the N per-process registries can be
    merged post-mortem (`tpu-ir metrics --cluster`). Parameters pass
    through to the implementation below (keyword-only there)."""
    from ..obs import aggregate
    from ..obs.progress import tracked

    name = os.path.basename(os.path.normpath(os.fspath(index_dir)))
    with tracked("build", f"multihost:{name}",
                 phases=("pass1_tokenize", "global_tables",
                         "pass2_combine", "pass3_reduce", "finalize"),
                 config={"k": kwargs.get("k", 1),
                         "process": jax.process_index(),
                         "process_count": jax.process_count()}):
        meta = _build_index_multihost(corpus_paths, index_dir, **kwargs)
    aggregate.spool_write()
    return meta


def _build_index_multihost(
    corpus_paths: Sequence[str] | str,
    index_dir: str,
    *,
    k: int = 1,
    chargram_ks: Sequence[int] = (2, 3),
    compute_chargrams: bool = True,
    batch_docs: int = 50_000,  # see streaming.py: fewer lockstep steps
    keep_spills: bool = False,
    positions: bool = False,
    store: bool = False,
) -> "object":
    """End-to-end STREAMING multi-host index build over the global mesh.

    Every process: streams ITS slice of the corpus files through the
    chunked native scanner (C++ record split + analysis + incremental
    vocab — never holding the slice's tokens in RAM), spills temp-id
    batches to its local disk, agrees on the global docno/vocab tables
    host-side, then replays its batches as lockstep SPMD steps: each step
    deals the batch's occurrences over the process's device rows and runs
    the combiner + all_to_all shuffle + term-shard reduce program
    (sharded_build.py); each device's reduced output spills straight to
    its term shard. A final per-shard host sort (the same pass 3 as
    index/streaming.py) writes each process's addressable part files, so
    artifacts are byte-identical to the single-process streaming build at
    the same shard count. Process 0 writes the shared side artifacts.

    `positions=True` (format v2): a term shard's pairs combine documents
    from EVERY process, but each document's token stream lives on exactly
    one process — so each process writes its batches' position runs
    (keyed (term, doc, tf) + delta block) into a SHARED spill area, and
    the shard's pass-3 owner re-aligns the union by the part order
    (term asc, tf desc, doc asc), asserting exact agreement with the
    pair columns it just wrote.
    `index_dir` must be a filesystem all processes can write (the
    HDFS-equivalent assumption); token/pair spills stay on process-local
    disk. Memory per process = the vocab + one batch, like the
    single-device streaming build — a slice larger than RAM streams fine.

    Crash resume (the streaming build's pass-DAG resume,
    index/streaming.py, generalized to many processes): each process
    keeps a pass-1 manifest in ITS spill dir keyed by a config signature
    that pins its corpus slice (size+mtime), k, batch_docs, process
    index/count and device count. On restart every process first resumes
    its OWN pass-1 state (valid per-process: token spills are slice-local
    temp ids). Pass-2 and pass-3 artifacts depend on the GLOBAL tables,
    so they are only trusted when an allgather confirms EVERY process
    resumed — one fresh pass-1 anywhere can shift the global vocab/docno
    ids and silently mis-key every pair spill. When all agree, completed
    pass-2 batches are replayed host-side (doc_len/df/pair counts
    recovered from the atomic spills) while the device step is skipped in
    LOCKSTEP — the collective sequence stays identical across processes.

    Single-process, this degenerates to the SPMD streaming build over
    local devices.
    """
    import shutil

    import jax
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..analysis.native import make_chunked_tokenizer
    from ..collection import DocnoMapping, Vocab
    from ..index import format as fmt
    from ..index.builder import build_chargram_artifacts
    from ..index.positions import positions_name
    from ..index.streaming import PASS1_MANIFEST, _config_sig, _load_resume_state
    from ..obs.progress import report_progress
    from ..ops.postings import PAD_TERM
    from ..utils import JobReport
    from .mesh import SHARD_AXIS, make_mesh
    from .sharded_build import sharded_build_postings

    if isinstance(corpus_paths, (str, os.PathLike)):
        corpus_paths = [corpus_paths]
    pi, pc = jax.process_index(), jax.process_count()
    n_local = jax.local_device_count()
    s = pc * n_local
    os.makedirs(index_dir, exist_ok=True)
    if fmt.artifact_exists(index_dir, fmt.METADATA):
        # skip-if-exists, like the streaming build (reference JobConf
        # semantics): a completed index is never rebuilt in place
        return fmt.IndexMetadata.load(index_dir)
    spill_dir = os.path.join(index_dir, f"_spill-p{pi:03d}")
    pos_dir = os.path.join(index_dir, "_spill-pos")  # SHARED (see above)
    # SHARED text spills (store=True): each process spills its batches'
    # raw record bytes during pass 1 — the docstore fold's zero extra
    # corpus reads (VERDICT r4 next #5) — and process 0 assembles the
    # store after pass 3. Each spill carries its own docids, so assembly
    # needs no cross-process token state.
    text_dir = os.path.join(index_dir, "_spill-text")

    # --- pass-1 resume: per-process manifest against this exact config ---
    my_files = process_file_slice(corpus_paths, pi, pc)
    sig = _config_sig(
        my_files, k, s, s, positions, store,
        extra=(f"mh-pi={pi}", f"pc={pc}", f"nlocal={n_local}",
               f"batch={batch_docs}"))
    resume_state = _load_resume_state(spill_dir, sig)
    if resume_state is None:
        shutil.rmtree(spill_dir, ignore_errors=True)
    os.makedirs(spill_dir, exist_ok=True)
    if positions:
        os.makedirs(pos_dir, exist_ok=True)
    if store:
        os.makedirs(text_dir, exist_ok=True)
    report = JobReport("TermKGramDocIndexer", config={
        "k": k, "multihost": True, "process": pi, "process_count": pc,
        "batch_docs": batch_docs, "resumed": resume_state is not None})

    # --- pass 1: chunked tokenize my slice -> local temp-id spills ---
    my_docids: list[str] = []
    n_batches = 0
    batch_dev_caps: list[int] = []  # max per-device occupancy per batch
    if resume_state is not None:
        my_docids = resume_state.docids
        local_vocab = resume_state.vocab
        n_batches = resume_state.n_batches
        batch_dev_caps = [int(c) for c in resume_state.batch_occ]
        report.incr("Count.DOCS", len(my_docids))
        report.set_counter("pass1_resumed_batches", n_batches)
        report_progress("pass1_tokenize", advance=n_batches,
                        total=n_batches, docs_parsed=len(my_docids),
                        resumed_batches=n_batches)
    else:
        from ..index.streaming import run_pass1_spills

        tok = make_chunked_tokenizer(my_files, k=k, with_text=store)
        with report.phase("pass1_tokenize"):
            # the shared loop records the batch's max per-device
            # occupancy — pass 2 negotiates one global capacity from
            # these, with no second read of the spills
            (my_docids, local_vocab, n_batches, batch_dev_caps,
             spill_crcs, _doc_lens) = run_pass1_spills(
                    tok, spill_dir, batch_docs, store, report,
                    text_path_fn=lambda b: os.path.join(
                        text_dir, f"text-p{pi:03d}-{b:05d}.npz"),
                    batch_stat=lambda ids, lengths: np.bincount(
                        np.arange(len(lengths)) % n_local,
                        weights=lengths, minlength=n_local).max())
        # manifest LAST (atomic): its existence certifies pass 1, exactly
        # like the single-process streaming build; batch_occ holds the
        # per-batch PER-DEVICE occupancy caps here (the quantity pass 2's
        # capacity negotiation needs); spill_crc lets a restart verify
        # the spills' bytes before trusting them
        fmt.savez_atomic(
            os.path.join(spill_dir, PASS1_MANIFEST), sig=sig,
            docids=np.array(my_docids, dtype=np.str_),
            vocab=np.array(local_vocab, dtype=np.str_),
            n_batches=np.int64(n_batches),
            batch_occ=np.array(batch_dev_caps, dtype=np.int64),
            spill_crc=np.array(spill_crcs, dtype=np.str_))

    # --- agree on global tables (host-side allgather) ---
    report_progress("global_tables")
    with report.phase("global_tables"):
        global_docids = allgather_strings(my_docids)
        global_terms = allgather_strings(local_vocab)
        total_seen = int(multihost_utils.process_allgather(
            np.int64(len(my_docids))).sum())
        if total_seen != len(global_docids):
            raise ValueError("duplicate docids across the corpus")
        mapping = DocnoMapping(global_docids)
        vocab = Vocab(global_terms)
        num_docs = len(mapping)
        v = len(vocab)
        sorted_docids = np.array(global_docids, dtype=np.str_)
        # local temp id -> global sorted id
        rank = (np.searchsorted(np.array(global_terms, dtype=np.str_),
                                np.array(local_vocab, dtype=np.str_))
                .astype(np.int32) if local_vocab
                else np.zeros(0, np.int32))

    # --- pass 2: lockstep per-batch SPMD shuffle over the global mesh ---
    mesh = make_mesh(s)
    doc_len = np.zeros(num_docs + 1, np.int64)
    df_local = np.zeros(v, np.int64)       # my term shards' dfs
    num_pairs_by_shard: dict[int, int] = {}
    my_rows = [pi * n_local + dev for dev in range(n_local)]
    occurrences = 0
    with report.phase("pass2_combine"):
        # one shared batch shape for the whole job: the max per-device
        # occupancy was recorded at flush time, so the global capacity is
        # negotiated from in-memory integers — all steps reuse one
        # compiled program. The same allgather carries the resume flag:
        # pass-2 artifacts are only trusted when EVERY process resumed
        # pass 1 (see the docstring's agreement argument).
        local_cap = max(batch_dev_caps, default=1)
        dims = multihost_utils.process_allgather(np.array(
            [n_batches, local_cap, int(resume_state is not None)],
            np.int64))
        dims = np.asarray(dims).reshape(pc, 3)
        b_global = int(dims[:, 0].max())
        cap = int(dims[:, 1].max())
        all_resumed = bool(dims[:, 2].all())
        report_progress("pass2_combine", total=b_global)
        granule = 1 << 12
        from ..ops.postings import round_cap

        cap = round_cap(cap, granule)
        sh2 = NamedSharding(mesh, P(SHARD_AXIS, None))
        sh1 = NamedSharding(mesh, P(SHARD_AXIS))

        if not all_resumed:
            # a fresh pass-1 anywhere invalidates ALL pass-2/3 artifacts
            # (global ids may have shifted): drop my pair spills + my
            # rows' outputs; process 0 clears the shared position spills
            # AND any part/position rows no process owns under the
            # current config (a crashed run with more processes leaves
            # higher-numbered rows that would sit in the finished index
            # forever); a barrier keeps every step after the wipe
            for name in os.listdir(spill_dir):
                if name.startswith("pairs-"):
                    os.unlink(os.path.join(spill_dir, name))
            for row in my_rows:
                # both part formats: the crashed run may have written
                # under a different TPU_IR_FORMAT_VERSION pin
                for path in (os.path.join(index_dir,
                                          fmt.part_name(row, fv))
                             for fv in (fmt.FORMAT_VERSION,
                                        fmt.ARENA_FORMAT_VERSION)):
                    if os.path.exists(path):
                        os.unlink(path)
                ppath = os.path.join(index_dir, positions_name(row))
                if os.path.exists(ppath):
                    os.unlink(ppath)
            if pi == 0:
                stale = re.compile(
                    r"^(?:part|positions)-(\d+)\.(?:npz|arena)$")
                for name in os.listdir(index_dir):
                    m = stale.match(name)
                    if m and int(m.group(1)) >= s:
                        os.unlink(os.path.join(index_dir, name))
                if positions:
                    for name in os.listdir(pos_dir):
                        os.unlink(os.path.join(pos_dir, name))
            multihost_utils.sync_global_devices("tpu_ir_stale_wiped")

        def my_batch_done(b: int) -> bool:
            """Did MY contribution to batch b land completely AND intact?
            Existence implies completeness (atomic files); a full read
            (zip entry CRCs) additionally proves the bytes, and a corrupt
            spill deletes the batch's local spills so ONLY that batch
            recomputes — in lockstep, because this flag rides the same
            allgather as everyone else's. Padding steps (b >= n_batches)
            still write empty pair spills, so the same check covers them;
            position spills exist only for real batches."""
            paths = [os.path.join(
                spill_dir, f"pairs-{row:03d}-{b:05d}.npz")
                for row in my_rows]
            if positions and b < n_batches:
                paths += [os.path.join(
                    pos_dir, f"pos-{row:03d}-b{b:05d}-p{pi:03d}.npz")
                    for row in range(s)]
            if not all(os.path.exists(p) for p in paths):
                return False
            if not all(fmt.readable_npz(p) for p in paths):
                from ..utils.report import recovery_counters

                recovery_counters().incr("spill_integrity_discards")
                for p in paths:
                    if os.path.exists(p):
                        os.unlink(p)
                return False
            return True

        done_local = np.array(
            [all_resumed and my_batch_done(b) for b in range(b_global)],
            np.int64)
        done_global = np.asarray(multihost_utils.process_allgather(
            done_local)).reshape(pc, b_global).all(axis=0)

        ofs = 0
        for b in range(b_global):
            if done_global[b]:
                # LOCKSTEP skip: every process skips this batch's device
                # step together (the collective sequence stays identical).
                # Host-side replay recovers what the step would have
                # produced: doc_len from the token spill's lengths, df and
                # pair counts from the pair spills (each spilled pair is
                # one (term, doc) -> df contribution of exactly 1).
                if b < n_batches:
                    with np.load(os.path.join(
                            spill_dir, f"tokens-{b:05d}.npz")) as z:
                        lengths = z["lengths"]
                    occurrences += int(lengths.sum())
                    docids = np.array(my_docids[ofs : ofs + len(lengths)],
                                      dtype=np.str_)
                    ofs += len(lengths)
                    docnos = (np.searchsorted(sorted_docids, docids) + 1
                              ).astype(np.int32)
                    doc_len[docnos] = lengths
                for row in my_rows:
                    with np.load(os.path.join(
                            spill_dir, f"pairs-{row:03d}-{b:05d}.npz")) as z:
                        t_sp = z["term"]
                        num_pairs_by_shard[row] = (
                            num_pairs_by_shard.get(row, 0) + len(t_sp))
                        df_local += np.bincount(t_sp, minlength=v)
                report.incr("pass2_resumed_batches", 1)
                report_progress("pass2_combine", advance=1,
                                resumed_batches=1)
                continue
            local_t = np.full((n_local, cap), PAD_TERM, np.int32)
            local_d = np.zeros((n_local, cap), np.int32)
            local_n = np.zeros(n_local, np.int32)
            if b < n_batches:  # processes out of batches step with padding
                with np.load(os.path.join(spill_dir,
                                          f"tokens-{b:05d}.npz")) as z:
                    flat, lengths = z["ids"], z["lengths"]
                occurrences += len(flat)
                term_ids = rank[flat]
                docids = np.array(my_docids[ofs : ofs + len(lengths)],
                                  dtype=np.str_)
                ofs += len(lengths)
                docnos = (np.searchsorted(sorted_docids, docids) + 1
                          ).astype(np.int32)
                doc_len[docnos] = lengths
                if positions:
                    _spill_position_runs(pos_dir, term_ids, docnos,
                                         lengths, s, b, pi)
                dev_of_doc = (np.arange(len(lengths)) % n_local).astype(
                    np.int32)
                flat_dev = np.repeat(dev_of_doc, lengths)
                flat_doc = np.repeat(docnos, lengths)
                for dev in range(n_local):
                    sel = flat_dev == dev
                    n_occ = int(sel.sum())
                    local_t[dev, :n_occ] = term_ids[sel]
                    local_d[dev, :n_occ] = flat_doc[sel]
                    local_n[dev] = int((dev_of_doc == dev).sum())
            g_t = jax.make_array_from_process_local_data(
                sh2, local_t, (s, cap))
            g_d = jax.make_array_from_process_local_data(
                sh2, local_d, (s, cap))
            g_n = jax.make_array_from_process_local_data(
                sh1, local_n, (s,))
            out = sharded_build_postings(
                g_t, g_d, g_n, vocab_size=v, total_docs=num_docs, mesh=mesh)

            # spill my devices' reduced outputs as their term shards'
            # pairs — shrunk + narrowed ON DEVICE first (the [S, C]
            # results are worst-case padded; every process computes the
            # same replicated global max so the sliced shapes agree)
            np_rows = {sd.index[0].start: int(np.asarray(sd.data).ravel()[0])
                       for sd in out.num_pairs.addressable_shards}
            npmax, tfmax = fetch_to_host(jnp.max(out.num_pairs),
                                         jnp.max(out.pair_tf))
            shrunk = {
                "pair_term": shrink_rows_for_fetch(
                    out.pair_term, int(npmax), dtype=narrow_uint(v - 1),
                    valid_rows=out.num_pairs),
                "pair_doc": shrink_rows_for_fetch(
                    out.pair_doc, int(npmax),
                    dtype=narrow_uint(num_docs),
                    valid_rows=out.num_pairs),
                "pair_tf": shrink_rows_for_fetch(
                    out.pair_tf, int(npmax), dtype=narrow_uint(int(tfmax)),
                    valid_rows=out.num_pairs),
            }
            rows = {}
            for col in ("pair_term", "pair_doc", "pair_tf"):
                rows[col] = {sd.index[0].start: np.asarray(sd.data)
                             .reshape(-1)
                             for sd in shrunk[col].addressable_shards}
            for row, npair in np_rows.items():
                fmt.savez_atomic(
                    os.path.join(spill_dir, f"pairs-{row:03d}-{b:05d}.npz"),
                    term=rows["pair_term"][row][:npair],
                    doc=rows["pair_doc"][row][:npair],
                    tf=rows["pair_tf"][row][:npair])
                num_pairs_by_shard[row] = (num_pairs_by_shard.get(row, 0)
                                           + npair)
            for sd in out.df.addressable_shards:
                df_local += np.asarray(sd.data).reshape(-1, v).sum(axis=0)
            report_progress("pass2_combine", advance=1,
                            spills_written=len(np_rows),
                            pairs=sum(np_rows.values()))
    report.set_counter("map_output_records", occurrences)
    report.set_counter("reduce_output_groups", v)

    # --- global side data (df / doc_len assembled across processes) ---
    with report.phase("reduce_side"):
        df = np.asarray(multihost_utils.process_allgather(df_local))
        df = df.reshape(-1, v).sum(axis=0).astype(np.int32)
        doc_len = np.asarray(multihost_utils.process_allgather(doc_len))
        doc_len = doc_len.reshape(-1, num_docs + 1).sum(axis=0).astype(
            np.int32)

    # --- pass 3: per-shard host sort for MY term shards (the same
    # reduce_shard_spills the single-process streaming build runs, so the
    # byte-identical-artifacts guarantee rests on one implementation) ---
    from ..index.streaming import reduce_shard_spills

    if positions:
        # a shard's position runs come from EVERY process's shared
        # spills; all writers must be done before any pass-3 reader
        multihost_utils.sync_global_devices("tpu_ir_pos_spills_done")
    report_progress("pass3_reduce", total=len(my_rows))
    with report.phase("pass3_reduce"):
        shard_of, offset_of = fmt.shard_local_offsets(df, s)
        for row in my_rows:
            # whichever format the crashed run wrote (see the wipe above)
            part = fmt.part_path(index_dir, row)
            # resume: an existing part (plus its positions file — written
            # AFTER the part here, so the pair must be checked together)
            # is this shard's final output from the crashed run. A part
            # whose full read fails (zipfile CRC) is corrupt: quarantine
            # it and rebuild only this shard from the spills, exactly
            # like the single-process streaming pass 3.
            npairs = None
            pos_ok = True
            if positions:
                ppath = os.path.join(index_dir, positions_name(row))
                pos_ok = os.path.exists(ppath)
                if pos_ok and not fmt.readable_npz(ppath):
                    # corrupt positions output: quarantine and rebuild
                    # the shard, or its rotten bytes get checksummed as
                    # authoritative (same rule as streaming pass 3)
                    fmt.quarantine(index_dir, positions_name(row))
                    report.incr("Fault.QUARANTINED_PARTS", 1)
                    pos_ok = False
            if all_resumed and pos_ok and os.path.exists(part):
                try:
                    npairs = len(fmt.load_shard(index_dir, row)["pair_doc"])
                    report.incr("pass3_resumed_shards", 1)
                    report_progress("pass3_reduce", advance=1,
                                    resumed_shards=1)
                except fmt.CORRUPT_NPZ:
                    fmt.quarantine(index_dir, os.path.basename(part))
                    report.incr("Fault.QUARANTINED_PARTS", 1)
            if npairs is None:
                _, npairs = reduce_shard_spills(
                    spill_dir, index_dir, row, b_global, v, shard_of)
                if positions:
                    _reduce_position_spills(pos_dir, index_dir, row)
            # cross-check: the sorted pair count must equal what pass 2's
            # device programs reported for this shard
            if npairs != num_pairs_by_shard.get(row, 0):
                raise AssertionError(
                    f"shard {row}: pass 3 saw {npairs} pairs but pass 2 "
                    f"reported {num_pairs_by_shard.get(row, 0)}")

    # --- process 0 writes shared side artifacts ---
    # barrier FIRST: metadata certifies the whole index, and its existence
    # is the skip-if-exists/resume gate — it must never be written while
    # another process still owes part files (a crash there would otherwise
    # leave a "complete" index missing shards forever)
    multihost_utils.sync_global_devices("tpu_ir_pass3_done")
    report_progress("finalize")
    if pi == 0:
        if store:
            # assemble the document store from every process's pass-1
            # text spills (process-major arrival order; each spill is
            # self-describing with its docids) — the corpus is never
            # re-read. dims[:, 0] holds each process's batch count.
            from ..index.docstore import (iter_text_spill_docnos,
                                          write_docstore)

            with report.phase("docstore"):
                def records():
                    for p in range(pc):
                        for b in range(int(dims[p, 0])):
                            yield from iter_text_spill_docnos(
                                os.path.join(
                                    text_dir,
                                    f"text-p{p:03d}-{b:05d}.npz"),
                                sorted_docids)

                stats = write_docstore(index_dir, records(), num_docs)
                report.set_counter("docstore_raw_bytes",
                                   stats["raw_bytes"])
                report.set_counter("docstore_stored_bytes",
                                   stats["stored_bytes"])
        mapping.save(os.path.join(index_dir, fmt.DOCNOS))
        vocab.save(os.path.join(index_dir, fmt.VOCAB))
        np.save(os.path.join(index_dir, fmt.DOCLEN), doc_len)
        # offsets were derived from the global df, so process 0 holds them all
        fmt.write_dictionary(index_dir, vocab.terms, shard_of, offset_of)
        built_chargrams = bool(compute_chargrams and chargram_ks and k == 1)
        if built_chargrams:
            build_chargram_artifacts(index_dir, vocab.terms,
                                     list(chargram_ks))
        meta = fmt.IndexMetadata(
            num_docs=num_docs, vocab_size=v, k=k, num_shards=s,
            num_pairs=int(df.sum()),
            chargram_ks=list(chargram_ks) if built_chargrams else [],
            version=2 if positions else fmt.FORMAT_VERSION,
            has_positions=bool(positions),
            format_version=fmt.resolve_format_version())
        # after the pass-3 barrier every process's parts exist, so
        # process 0 can checksum the whole artifact set
        meta.save_with_checksums(index_dir)
        report.save(os.path.join(index_dir, fmt.JOBS_DIR))
    multihost_utils.sync_global_devices("tpu_ir_index_built")
    # spills only AFTER metadata certifies the index: a peer crashing in
    # pass 3 must find every survivor's resume state intact on restart
    # (deleting earlier made the zero-step resume a kill-timing race)
    if not keep_spills:
        shutil.rmtree(spill_dir, ignore_errors=True)
        if pi == 0:
            if positions:
                shutil.rmtree(pos_dir, ignore_errors=True)
            if store:
                shutil.rmtree(text_dir, ignore_errors=True)
    return fmt.IndexMetadata.load(index_dir)


def _spill_position_runs(pos_dir: str, term_ids: np.ndarray,
                         docnos: np.ndarray, lengths: np.ndarray,
                         num_shards: int, b: int, pi: int) -> None:
    """One batch's position runs -> shared per-term-shard spill files
    carrying their (term, doc, tf) run keys, so the pass-3 shard owner
    can re-align the union from every process by the part order."""
    from ..index import format as fmt2
    from ..index.positions import (build_position_runs,
                                   flat_positions_from_lengths,
                                   realign_runs)

    flat_doc = np.repeat(np.asarray(docnos, np.int64),
                         np.asarray(lengths, np.int64))
    flat_pos = flat_positions_from_lengths(lengths)
    rt, rd, rtf, idp, delta = build_position_runs(term_ids, flat_doc,
                                                  flat_pos)
    run_len = np.diff(idp)
    shard = rt.astype(np.int64) % num_shards
    for row in range(num_shards):
        sel = shard == row
        indptr, gather = realign_runs(idp[:-1][sel], run_len[sel])
        fmt2.savez_atomic(
            os.path.join(pos_dir, f"pos-{row:03d}-b{b:05d}-p{pi:03d}.npz"),
            term=rt[sel], doc=rd[sel], tf=rtf[sel],
            pos_indptr=indptr.astype(np.int64),
            pos_delta=delta[gather].astype(np.int32))


def _reduce_position_spills(pos_dir: str, index_dir: str, row: int) -> None:
    """Pass 3 for ONE shard's positions: union every process's run spills
    for the shard, lexsort runs into the part order (term asc, tf desc,
    doc asc), assert EXACT agreement with the freshly-written part file's
    pair columns, write positions-NNNNN.npz."""
    import glob

    from ..index import format as fmt2
    from ..index.positions import positions_name, realign_runs

    terms, docs, tfs, deltas, rlens = [], [], [], [], []
    for path in sorted(glob.glob(
            os.path.join(pos_dir, f"pos-{row:03d}-b*-p*.npz"))):
        with np.load(path) as z:
            terms.append(z["term"])
            docs.append(z["doc"])
            tfs.append(z["tf"])
            deltas.append(z["pos_delta"])
            rlens.append(np.diff(z["pos_indptr"]))
    rt = np.concatenate(terms) if terms else np.zeros(0, np.int32)
    rd = np.concatenate(docs) if docs else np.zeros(0, np.int32)
    rtf = np.concatenate(tfs) if tfs else np.zeros(0, np.int32)
    delta = (np.concatenate(deltas) if deltas else np.zeros(0, np.int32))
    rlen = (np.concatenate(rlens).astype(np.int64) if rlens
            else np.zeros(0, np.int64))
    order = np.lexsort((rd, -rtf.astype(np.int64), rt))
    starts = np.concatenate([[0], np.cumsum(rlen)])[:-1]
    new_len = rlen[order]
    out_indptr, gather = realign_runs(starts[order], new_len)
    # alignment proof against the part file this process just wrote
    z = fmt2.load_shard(index_dir, row)
    if not (np.array_equal(rd[order], z["pair_doc"])
            and np.array_equal(rtf[order], z["pair_tf"])
            and np.array_equal(new_len, z["pair_tf"])):
        raise AssertionError(
            f"shard {row}: position runs do not align with pair columns")
    fmt2.savez_atomic(
        os.path.join(index_dir, positions_name(row)),
        pos_indptr=out_indptr.astype(np.int64),
        pos_delta=delta[gather].astype(np.int32))


ALLGATHER_CHUNK_BYTES = 4 << 20


def allgather_strings(local: Sequence[str],
                      chunk_bytes: int = ALLGATHER_CHUNK_BYTES) -> list[str]:
    """Union of string sets across processes (sorted). Uses host-side
    broadcast through the jax coordination service; single-process = sorted
    unique of the input.

    The exchange is CHUNKED: each process serializes its sorted set as one
    newline-joined UTF-8 blob and the blobs cross in fixed-size rounds, so
    peak exchange memory is O(P * chunk_bytes) per process — never the
    padded [P, rows, max_width] matrix of the round-2 implementation,
    which at millions of terms materialized multiple GB on every host
    (VERDICT r2 item 5). The OUTPUT (the global table every process must
    hold, like the reference's side-data broadcast of the docno mapping)
    still scales with the global set; only the transport is bounded.
    Every process must call with the same chunk_bytes (the round count is
    negotiated from the global max blob length, so the collective call
    sequence stays lockstep)."""
    if jax.process_count() == 1:
        return sorted(set(local))
    from jax.experimental import multihost_utils

    for s in local:
        # '\n' is the wire separator: an embedded newline (the multi-line
        # <DOCNO> case DocnoMapping rejects) would silently split into
        # two entries here, BEFORE any validation — surface the same
        # corpus error the single-process build raises
        if "\n" in s or "\r" in s:
            raise ValueError(f"string {s!r} contains a newline and cannot "
                             "cross the allgather; fix the corpus record")
    blob = b"\n".join(s.encode("utf-8") for s in sorted(set(local)))
    n = len(blob)
    sizes = np.asarray(multihost_utils.process_allgather(
        np.int64(n))).reshape(-1)                            # [P]
    max_n = int(sizes.max())
    out: set[str] = set()
    tails = [b""] * len(sizes)  # carry a line split across round edges
    for ofs in range(0, max_n, chunk_bytes):
        width = min(chunk_bytes, max_n - ofs)
        chunk = np.zeros(width, np.uint8)
        if ofs < n:
            piece = blob[ofs : ofs + width]
            chunk[: len(piece)] = np.frombuffer(piece, np.uint8)
        gathered = np.asarray(
            multihost_utils.process_allgather(chunk))        # [P, width]
        for p in range(len(sizes)):
            valid = max(0, min(int(sizes[p]) - ofs, width))
            if not valid:
                continue
            *lines, tails[p] = (tails[p]
                                + bytes(gathered[p, :valid])).split(b"\n")
            out.update(ln.decode("utf-8") for ln in lines)
    for tail in tails:
        if tail:
            out.add(tail.decode("utf-8"))
    return sorted(out)
