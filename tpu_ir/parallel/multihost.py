"""Multi-host (multi-process) SPMD support.

The reference scaled by adding Hadoop task trackers; tpu-ir scales by adding
hosts to the jax.distributed job. The same shard_map programs run unchanged:
a global mesh over all devices of all hosts, collectives riding ICI within a
slice and DCN across slices — the framework code is host-count-agnostic
(SURVEY.md §4 "host-count-agnostic SPMD code").

Responsibilities handled here:
- process bootstrap (`init_distributed`) wrapping jax.distributed.initialize;
- corpus partitioning across processes (`process_file_slice`): each host
  streams only its slice of the input files, the moral equivalent of HDFS
  locality-aware splits;
- global docno/vocab agreement: each host tokenizes its slice, then the
  docid and term sets are exchanged host-side (allgather over the process
  group via jax.experimental.multihost_utils) so every process holds the
  same sorted global tables before the device build runs.

Single-process calls are no-ops/identities, so the same driver script runs
everywhere.
"""

from __future__ import annotations

import os
from typing import Sequence

import jax
import numpy as np


def init_distributed(coordinator: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> tuple[int, int]:
    """Initialize jax.distributed when running multi-process; returns
    (process_index, process_count). Safe to call single-process (no-op)."""
    if coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS"):
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id)
    return jax.process_index(), jax.process_count()


def process_file_slice(paths: Sequence[str],
                       process_index: int | None = None,
                       process_count: int | None = None) -> list[str]:
    """Deterministic round-robin assignment of corpus files to processes.

    Every process must call with the same (sorted) path list. Files, not
    byte-ranges, are the split unit — the streaming reader handles any file
    size, and TREC corpora ship as many files."""
    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    expanded: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            expanded.extend(
                os.path.join(p, n) for n in sorted(os.listdir(p))
                if os.path.isfile(os.path.join(p, n)))
        else:
            expanded.append(p)
    return [f for i, f in enumerate(expanded) if i % pc == pi]


def allgather_strings(local: Sequence[str]) -> list[str]:
    """Union of string sets across processes (sorted). Uses host-side
    broadcast through the jax coordination service; single-process = sorted
    unique of the input."""
    if jax.process_count() == 1:
        return sorted(set(local))
    from jax.experimental import multihost_utils

    # encode local strings as a padded uint8 matrix; negotiate the global
    # matrix shape first (hosts have different set sizes), then allgather.
    blobs = [s.encode("utf-8") for s in sorted(set(local))]
    max_len = max((len(b) for b in blobs), default=1)
    dims = multihost_utils.process_allgather(
        np.array([len(blobs), max_len], np.int64))          # [P, 2]
    rows = int(dims[:, 0].max())
    width = int(dims[:, 1].max())
    arr = np.zeros((max(rows, 1), width), np.uint8)
    for i, b in enumerate(blobs):
        arr[i, : len(b)] = np.frombuffer(b, np.uint8)
    gathered = np.asarray(multihost_utils.process_allgather(arr))  # [P, R, W]
    out: set[str] = set()
    for row in gathered.reshape(-1, width):
        b = bytes(row).rstrip(b"\x00")
        if b:
            out.add(b.decode("utf-8"))
    return sorted(out)
