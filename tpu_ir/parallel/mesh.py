"""Mesh construction and backend selection.

The reference's "cluster vs local mode" switch (mapred.job.tracker == "local",
TermKGramDocIndexer.java:101-108) becomes backend selection: the same SPMD
program runs on a TPU slice, a single chip, or N virtual CPU devices
(XLA_FLAGS=--xla_force_host_platform_device_count=N) — SURVEY.md §2.5.

One mesh axis, "shards": for the index build each device plays both mapper
(its doc shard) and reducer (its term shard), exchanging postings over
all_to_all — the direct analog of Hadoop's N map tasks feeding N reduce
partitions through the shuffle, except the "shuffle" is one XLA collective
over ICI.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

SHARD_AXIS = "shards"

# jax moved shard_map out of experimental (and renamed check_rep ->
# check_vma) late in the 0.4.x line; accept either API so the SPMD paths
# run on whatever jax the container ships instead of dying at dispatch
# with AttributeError
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
        if check_vma is not None:
            kw["check_rep"] = check_vma
        return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kw)


def make_mesh(num_shards: int | None = None, backend: str | None = None) -> Mesh:
    devices = jax.devices(backend) if backend else jax.devices()
    if num_shards is None:
        num_shards = len(devices)
    if num_shards > len(devices):
        raise ValueError(
            f"need {num_shards} devices, have {len(devices)} "
            "(for CPU testing set XLA_FLAGS=--xla_force_host_platform_device_count=N)")
    return Mesh(np.array(devices[:num_shards]), (SHARD_AXIS,))
