"""Distributed tiered serving: the tiered layout's doc axis sharded over the
mesh, with TF-IDF, BM25 and two-stage rerank all running as SPMD programs.

This is the serving path that scales past one device's HBM: docnos 1..D are
split into contiguous blocks of `dblk` and each device holds the FULL tiered
structure (search/layout.py: budget-capped hot strip + geometric-capacity df
tiers) for its block only — total memory is the single-device layout spread
over the mesh, not replicated (the round-1 dense [S, V, Dblk] demo held V*D
in total and could not hold the corpora that need distribution).

Scoring one query block:
  1. every device runs the tiered accumulation over its [B, dblk+1] slice
     (one hot matmul + one masked gather/scatter per tier — ops/scoring.py
     `_tiered_scores`, the same code the single-device sparse layout runs);
  2. local top-k, then an all_gather of k*S candidates and a replicated
     merge — the standard distributed top-k: k*S candidates cross ICI
     instead of D scores.
Rerank runs both stages inside one shard_map body: BM25 candidates are
merged exactly as above, then each device scores the cosine stage for the
candidates that fall in its block and a psum assembles the [B, C] candidate
scores (each candidate lives on exactly one device).

The reference has no distributed serving (a single JVM doing disk seeks,
SURVEY.md §3.3); the mesh/collective structure is the TPU answer to the
same corpus-partitioning idea its MapReduce build used (SURVEY.md §2.5).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops.scoring import (_lntf, _tiered_scores, _topk_over_candidates,
                           bm25_idf_weights, bm25_saturation, idf_weights)
from ..obs.profiling import profiled_jit
from ..search.layout import BASE_CAP, GROWTH, HOT_BUDGET, build_tiered_layout
from .mesh import SHARD_AXIS, shard_map


class ShardedTieredLayout(NamedTuple):
    """Host or device arrays, every leaf carrying a leading [S] shard axis.

    Local docnos are 1..dblk (0 = empty slot); global docno = local +
    doc_base[s]. hot/tier semantics per shard are exactly
    search/layout.py's, built over the shard's doc block."""

    hot_rank: object   # int32 [S, V] row in hot_tfs or -1
    hot_tfs: object    # f32 [S, H, dblk+1] raw tf
    tier_of: object    # int32 [S, V] tier index or -1
    row_of: object     # int32 [S, V]
    tier_docs: tuple   # of int32 [S, V_t, P_t] local docnos
    tier_tfs: tuple    # of int32 [S, V_t, P_t]
    doc_len: object    # int32 [S, dblk+1] local doc lengths (slot 0 dead)
    doc_base: object   # int32 [S] global docno offset of the block
    dblk: int          # static block width


def shard_slices(global_row: np.ndarray, *, num_docs: int, num_shards: int,
                 fill=0) -> np.ndarray:
    """Split a global [D+1] doc-axis row (norms, lengths...) into the
    sharded [S, dblk+1] local form (slot 0 dead per shard)."""
    dblk = -(-num_docs // num_shards)
    out = np.full((num_shards, dblk + 1), fill, global_row.dtype)
    for s in range(num_shards):
        lo, hi = s * dblk + 1, min((s + 1) * dblk, num_docs)
        if hi < lo:  # trailing shards past num_docs hold no docs at all
            continue
        out[s, 1 : hi - lo + 2] = global_row[lo : hi + 1]
    return out


def make_sharded_tiered(
    pair_term: np.ndarray,
    pair_doc: np.ndarray,
    pair_tf: np.ndarray,
    df: np.ndarray,
    doc_len: np.ndarray,
    *,
    num_docs: int,
    num_shards: int,
    hot_budget: int = HOT_BUDGET,
    base_cap: int = BASE_CAP,
    growth: int = GROWTH,
) -> ShardedTieredLayout:
    """Host-side: global-CSR postings -> per-shard tiered layouts, stacked.

    Each shard's layout is built by the single-device builder over the
    shard's postings (doc range remapped to local 1..dblk). Tier capacities
    come from the shared (base_cap, growth) ladder, so stacking only needs
    to align each shard's tiers to the union of capacities present and pad
    row counts to the per-tier max."""
    v = len(df)
    dblk = -(-num_docs // num_shards)
    per = []
    for s in range(num_shards):
        lo, hi = s * dblk + 1, min((s + 1) * dblk, num_docs)
        sel = (pair_doc >= lo) & (pair_doc <= hi)
        # masking preserves the global (term asc, tf desc, doc asc) order,
        # so the selected columns are term-major runs of length df_local —
        # exactly the contract build_tiered_layout needs
        df_l = np.bincount(pair_term[sel], minlength=v).astype(np.int64)
        per.append(build_tiered_layout(
            (pair_doc[sel] - (lo - 1)).astype(np.int32), pair_tf[sel], df_l,
            num_docs=dblk,
            hot_budget=max(hot_budget // num_shards, dblk + 1),
            base_cap=base_cap, growth=growth))

    # hot strip: pad rows to the max across shards (densified per shard on
    # host — each shard's strip is 1/S of the global one, and put_sharded
    # uploads only each device's own slice)
    h_max = max(t.num_hot for t in per)
    hot_tfs = np.zeros((num_shards, h_max, dblk + 1), np.float32)
    hot_rank = np.stack([t.hot_rank for t in per])
    for s, t in enumerate(per):
        hot_tfs[s, : t.num_hot] = t.hot_dense()

    # tiers: align to the union capacity ladder, pad rows per rung
    u_caps = sorted({td.shape[1] for t in per for td in t.tier_docs})
    rung_of_cap = {c: j for j, c in enumerate(u_caps)}
    rows = [1] * len(u_caps)
    for t in per:
        for td in t.tier_docs:
            j = rung_of_cap[td.shape[1]]
            rows[j] = max(rows[j], td.shape[0])
    tier_docs = [np.zeros((num_shards, rows[j], c), np.int32)
                 for j, c in enumerate(u_caps)]
    tier_tfs = [np.zeros((num_shards, rows[j], c), np.int32)
                for j, c in enumerate(u_caps)]
    tier_of = np.full((num_shards, v), -1, np.int32)
    row_of = np.zeros((num_shards, v), np.int32)
    for s, t in enumerate(per):
        lut = np.array([rung_of_cap[td.shape[1]] for td in t.tier_docs],
                       np.int32)
        local = t.tier_of >= 0
        tier_of[s][local] = lut[t.tier_of[local]]
        row_of[s] = t.row_of
        for i, (td, tt) in enumerate(zip(t.tier_docs, t.tier_tfs)):
            j = int(lut[i])
            tier_docs[j][s, : td.shape[0]] = td
            tier_tfs[j][s, : tt.shape[0]] = tt

    dl = shard_slices(np.asarray(doc_len, np.int32), num_docs=num_docs,
                      num_shards=num_shards)
    doc_base = (np.arange(num_shards, dtype=np.int32) * dblk)

    return ShardedTieredLayout(
        hot_rank, hot_tfs, tier_of, row_of,
        tuple(tier_docs), tuple(tier_tfs), dl, doc_base, dblk)


def restrict_sharded_layout(lay: ShardedTieredLayout, lo: int,
                            hi: int) -> ShardedTieredLayout:
    """Doc-range-restricted COPY of a sharded layout (the scatter-gather
    worker entry, search/layout.py::restrict_tiers's SPMD sibling):
    postings whose GLOBAL docno (local + doc_base) falls outside
    [lo, hi] have their tf zeroed; shapes, geometry and every in-range
    posting are untouched, so the SPMD programs trace identically and
    in-range docs score bit-identically to the unrestricted layout —
    the exact-merge correctness argument, distributed form."""
    hot = np.array(lay.hot_tfs)          # [S, H, dblk+1]; may be mmap
    doc_base = np.asarray(lay.doc_base).astype(np.int64)
    n_shards = hot.shape[0]
    # global docno of each local column, per device shard (column 0 is
    # the dead slot — already excluded by the kernels, zero it anyway)
    local = np.arange(hot.shape[-1], dtype=np.int64)[None, :]
    g = local + doc_base[:, None]                        # [S, dblk+1]
    col_out = (g < lo) | (g > hi) | (local == 0)
    hot[np.broadcast_to(col_out[:, None, :], hot.shape)] = 0.0
    tier_tfs = []
    for td, tt in zip(lay.tier_docs, lay.tier_tfs):
        td64 = np.asarray(td).astype(np.int64)           # [S, V_t, P_t]
        gd = td64 + doc_base[:n_shards, None, None]
        tf = np.array(tt)
        tf[(td64 == 0) | (gd < lo) | (gd > hi)] = 0
        tier_tfs.append(tf)
    return lay._replace(hot_tfs=hot, tier_tfs=tuple(tier_tfs))


def _sharded_cache_key(index_dir: str, meta, num_shards: int,
                       part_crcs: dict | None = None) -> dict:
    from ..search.layout import _serving_cache_key

    return dict(_serving_cache_key(index_dir, meta,
                                   HOT_BUDGET, BASE_CAP, GROWTH,
                                   part_crcs=part_crcs),
                kind="sharded", num_shards=num_shards)


def load_sharded_serving_cache(index_dir: str, *, meta, num_shards: int):
    """Sharded-serving-cache hit: (ShardedTieredLayout, df, doc_norms) with
    NO shard IO — or None on any miss. Per shard count
    (`serving-sharded-N/`): a different mesh size needs different doc
    blocks. The stacked hot strip is stored as COO (a dense [S, H, dblk+1]
    f32 strip is ~2 GB of mostly zeros at 1M docs) and densified here on
    host — the same bytes-on-disk reasoning as the single-device cache's
    v2 format (search/layout.py)."""
    from ..search.layout import (_part_stat, cache_revalidate_mode,
                                 read_cache_manifest)

    cache_revalidate_mode()  # a bogus knob raises HERE, not into except
    try:
        hit = read_cache_manifest(
            index_dir, f"serving-sharded-{num_shards}",
            lambda part_crcs=None: _sharded_cache_key(
                index_dir, meta, num_shards, part_crcs=part_crcs),
            part_stat=lambda: _part_stat(index_dir, meta))
        if hit is None:
            return None
        m, arr = hit
        hot_tfs = np.zeros(tuple(m["hot_shape"]), np.float32)
        hot_tfs.reshape(-1)[np.asarray(arr("hot_flat_idx"))] = \
            arr("hot_vals")
        lay = ShardedTieredLayout(
            arr("hot_rank"), hot_tfs, arr("tier_of"), arr("row_of"),
            tuple(arr(f"tier_docs_{i}") for i in range(m["num_tiers"])),
            tuple(arr(f"tier_tfs_{i}") for i in range(m["num_tiers"])),
            arr("doc_len"), arr("doc_base"), m["dblk"])
        return lay, arr("df"), arr("doc_norms")
    except (OSError, KeyError, ValueError):
        return None


def save_sharded_serving_cache(index_dir: str, lay: ShardedTieredLayout,
                               df: np.ndarray, doc_norms: np.ndarray, *,
                               meta, num_shards: int) -> None:
    """Persist via the shared atomic cache protocol
    (search/layout.py::write_cache_atomic); any failure leaves the
    in-memory layout in charge."""
    from ..search.layout import _part_stat, _slim, write_cache_atomic

    hot = np.asarray(lay.hot_tfs)
    flat_idx = np.flatnonzero(hot.reshape(-1))
    arrays = {
        "hot_rank": lay.hot_rank,
        "hot_flat_idx": flat_idx,
        "hot_vals": _slim(hot.reshape(-1)[flat_idx].astype(np.int64),
                          int(hot.max(initial=0)) + 1),
        "tier_of": lay.tier_of, "row_of": lay.row_of,
        "doc_len": lay.doc_len, "doc_base": lay.doc_base,
        "df": np.asarray(df, np.int32),
        "doc_norms": np.asarray(doc_norms, np.float32),
    }
    for i, (d, t) in enumerate(zip(lay.tier_docs, lay.tier_tfs)):
        arrays[f"tier_docs_{i}"] = d
        arrays[f"tier_tfs_{i}"] = t
    write_cache_atomic(
        index_dir, f"serving-sharded-{num_shards}", arrays,
        lambda: {"key": _sharded_cache_key(
                     index_dir, meta, num_shards,
                     part_crcs=getattr(meta, "checksums", None)),
                 "part_stat": _part_stat(index_dir, meta),
                 "num_tiers": len(lay.tier_docs),
                 "hot_shape": list(np.asarray(lay.hot_tfs).shape),
                 "dblk": lay.dblk})


def _put_global(a, mesh, spec):
    """Host array -> global jax.Array under `spec`, valid whether the mesh
    is single-process or spans processes. Multi-process placement goes
    through make_array_from_callback: each process materializes only the
    index slices its addressable devices own (jax.device_put of a host
    array cannot place data on non-addressable devices — the round-2 gap
    that kept sharded serving single-process, VERDICT r2 missing #1)."""
    a = np.asarray(a)
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(a, sharding)
    return jax.make_array_from_callback(a.shape, sharding,
                                        lambda idx: a[idx])


def replicated_global(a, mesh):
    """Replicate a host value over every device of a (possibly
    multi-process) mesh. Single-process: the value passes through
    untouched, so the measured single-chip query path is unchanged.
    Idempotent: an already-replicated jax.Array passes through, so
    callers that replicate once at init (Scorer's sharded df) don't
    re-upload per dispatched query block."""
    if jax.process_count() == 1:
        return a
    if (isinstance(a, jax.Array)
            and a.sharding == NamedSharding(mesh, P())):
        return a
    return _put_global(a, mesh, P())


def put_doc_sharded(a, mesh):
    """[S, ...] host row-block array -> mesh, one row per device (used for
    the rerank's sharded doc norms)."""
    a = np.asarray(a)
    return _put_global(a, mesh, P(SHARD_AXIS, *([None] * (a.ndim - 1))))


def put_sharded(layout: ShardedTieredLayout, mesh) -> ShardedTieredLayout:
    """Move a host layout to the mesh: every array sharded on its leading
    axis (one shard slice per device)."""

    def put(a):
        return put_doc_sharded(a, mesh)

    return ShardedTieredLayout(
        put(layout.hot_rank), put(layout.hot_tfs), put(layout.tier_of),
        put(layout.row_of), tuple(put(a) for a in layout.tier_docs),
        tuple(put(a) for a in layout.tier_tfs), put(layout.doc_len),
        put(layout.doc_base), layout.dblk)


def _bm25_weight_fns(doc_len, n_f, k1, b):
    """(hot_fn, cold_fn) closing over this shard's [dblk+1] length norms;
    avg_dl is the GLOBAL mean, assembled with a psum over the mesh."""
    dl = doc_len.astype(jnp.float32)
    total = jax.lax.psum(jnp.sum(dl), SHARD_AXIS)
    avg_dl = total / jnp.maximum(n_f, 1.0)
    dl_norm = 1.0 - b + b * dl / jnp.maximum(avg_dl, 1e-9)
    hot = lambda tf: bm25_saturation(tf, dl_norm[None, :], k1=k1)
    cold = lambda tfs, docs: bm25_saturation(tfs, dl_norm[docs], k1=k1)
    return hot, cold


def _local_scores(q_terms, q_weight, lay_local, *, dblk, scoring, n_f,
                  k1, b, hot_only=False):
    """[B, dblk+1] tiered scores for this shard (column 0 dead).
    `hot_only` (static) skips the cold-tier stages — the overload ladder's
    hot-tier-only service level, distributed form."""
    (hot_rank, hot_tfs, tier_of, row_of, tier_docs, tier_tfs,
     doc_len) = lay_local
    if scoring == "bm25":
        # lint: invariant-ok (per-shard weight-vector prep inside the SPMD
        # program; hoisting would add a host round-trip per dispatch)
        hot_fn, cold_fn = _bm25_weight_fns(doc_len, n_f, k1, b)
    else:
        hot_fn = _lntf
        cold_fn = lambda tfs, docs: _lntf(tfs)
    return _tiered_scores(
        q_terms, hot_rank, hot_tfs, tier_of, row_of, tier_docs, tier_tfs,
        q_weight, num_docs=dblk, hot_weight_fn=hot_fn, cold_weight_fn=cold_fn,
        skip_cold=hot_only)


def _merge_topk(scores, doc_base, k):
    """Local [B, dblk+1] scores -> replicated global (scores, docnos) top-k.
    Column 0 is the dead local slot; empty results carry docno 0."""
    scores = scores.at[:, 0].set(-jnp.inf)
    kk = min(k, scores.shape[-1])
    loc_s, loc_i = jax.lax.top_k(scores, kk)
    if kk < k:
        pad = k - kk
        loc_s = jnp.pad(loc_s, ((0, 0), (0, pad)), constant_values=-jnp.inf)
        loc_i = jnp.pad(loc_i, ((0, 0), (0, pad)))
    loc_d = loc_i.astype(jnp.int32) + doc_base
    all_s = jax.lax.all_gather(loc_s, SHARD_AXIS)   # [S, B, k]
    all_d = jax.lax.all_gather(loc_d, SHARD_AXIS)
    s, b_, _ = all_s.shape
    flat_s = jnp.transpose(all_s, (1, 0, 2)).reshape(b_, s * k)
    flat_d = jnp.transpose(all_d, (1, 0, 2)).reshape(b_, s * k)
    top_s, pos = jax.lax.top_k(flat_s, k)
    top_d = jnp.take_along_axis(flat_d, pos, axis=1)
    matched = top_s > 0.0
    return (jnp.where(matched, top_s, 0.0),
            jnp.where(matched, top_d, 0).astype(jnp.int32))


def _unpack_local(hot_rank, hot_tfs, tier_of, row_of, doc_len, doc_base,
                  tier_docs, tier_tfs):
    """Strip the leading per-device [1] axis shard_map leaves on inputs."""
    return ((hot_rank.reshape(hot_rank.shape[-1]),
             hot_tfs.reshape(hot_tfs.shape[-2:]),
             tier_of.reshape(tier_of.shape[-1]),
             row_of.reshape(row_of.shape[-1]),
             tuple(a.reshape(a.shape[-2:]) for a in tier_docs),
             tuple(a.reshape(a.shape[-2:]) for a in tier_tfs),
             doc_len.reshape(doc_len.shape[-1])),
            doc_base.reshape(()))


@partial(profiled_jit,
         static_argnames=("mesh", "k", "scoring", "compat_int_idf",
                          "k1", "b", "dblk", "hot_only"))
def _sharded_topk_jit(q_terms, df, n_scalar, hot_rank, hot_tfs, tier_of,
                      row_of, doc_len, doc_base, tier_docs, tier_tfs, *,
                      mesh, dblk, k, scoring, compat_int_idf, k1, b,
                      hot_only=False):
    n_f = jnp.asarray(n_scalar, jnp.float32)
    if scoring == "bm25":
        # lint: invariant-ok (per-shard weight-vector prep inside the SPMD
        # program; hoisting would add a host round-trip per dispatch)
        q_weight = bm25_idf_weights(df, n_f)
    else:
        # lint: invariant-ok (per-shard weight-vector prep inside the SPMD
        # program; hoisting would add a host round-trip per dispatch)
        q_weight = idf_weights(df, n_scalar, compat_int_idf)

    def body(q, qw, *leaves):
        lay, base = _unpack_local(*leaves)
        scores = _local_scores(q, qw, lay, dblk=dblk, scoring=scoring,
                               n_f=n_f, k1=k1, b=b, hot_only=hot_only)
        return _merge_topk(scores, base, k)

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(None, None), P(None)) + _layout_specs_flat(tier_docs),
        out_specs=(P(None, None), P(None, None)),
        # the merge is an identical all_gather+top_k on every device, so
        # the outputs are replicated by construction
        check_vma=False)
    return fn(q_terms, q_weight, hot_rank, hot_tfs, tier_of, row_of,
              doc_len, doc_base, tier_docs, tier_tfs)


def _layout_specs_flat(tier_docs):
    sh2 = P(SHARD_AXIS, None)
    sh3 = P(SHARD_AXIS, None, None)
    n_t = len(tier_docs)
    return (sh2, sh3, sh2, sh2, sh2, P(SHARD_AXIS),
            tuple(sh3 for _ in range(n_t)), tuple(sh3 for _ in range(n_t)))


def sharded_tiered_topk(q_terms, layout: ShardedTieredLayout, df, num_docs,
                        *, mesh, k: int = 10, scoring: str = "tfidf",
                        compat_int_idf: bool = False,
                        k1: float = 0.9, b: float = 0.4,
                        hot_only: bool = False):
    """Batched distributed top-k over the sharded tiered layout.
    Returns (scores [B, k], docnos [B, k]); docno 0 marks an empty slot.
    Multi-process: per-call inputs are replicated over the global mesh
    (outputs come back replicated, so every process can read them).
    `hot_only` scores just the per-shard hot strips (the overload
    ladder's cheapest device level; partial scores, caller tags them)."""
    q_terms = replicated_global(q_terms, mesh)
    df = replicated_global(df, mesh)
    num_docs = replicated_global(np.int32(num_docs), mesh)
    return _sharded_topk_jit(
        q_terms, df, num_docs, layout.hot_rank, layout.hot_tfs,
        layout.tier_of, layout.row_of, layout.doc_len, layout.doc_base,
        layout.tier_docs, layout.tier_tfs, mesh=mesh, dblk=layout.dblk,
        k=k, scoring=scoring, compat_int_idf=compat_int_idf, k1=k1, b=b,
        hot_only=hot_only)


def _gather_candidates(scores, cand, doc_base, dblk):
    """Read local [B, dblk+1] scores out at global docnos `cand` [B, C]:
    the owning shard contributes its value, every other shard exact 0.0,
    and the psum assembles the replicated [B, C] result — the same
    each-candidate-lives-on-one-device idiom the production rerank's
    stage 2 uses, so gathered floats equal what _merge_topk saw."""
    li = cand - doc_base                                  # local 1..dblk
    in_blk = (li >= 1) & (li <= dblk) & (cand > 0)
    safe = jnp.where(in_blk, li, 0)
    cs = jnp.take_along_axis(scores, safe, axis=1) * in_blk
    return jax.lax.psum(cs, SHARD_AXIS)


@partial(profiled_jit,
         static_argnames=("mesh", "scoring", "compat_int_idf", "k1", "b",
                          "dblk", "hot_only"))
def _sharded_scores_at_jit(q_terms, df, n_scalar, cand, hot_rank, hot_tfs,
                           tier_of, row_of, doc_len, doc_base, tier_docs,
                           tier_tfs, *, mesh, dblk, scoring,
                           compat_int_idf, k1, b, hot_only=False):
    n_f = jnp.asarray(n_scalar, jnp.float32)
    if scoring == "bm25":
        # lint: invariant-ok (per-shard weight-vector prep inside the SPMD
        # program; hoisting would add a host round-trip per dispatch)
        q_weight = bm25_idf_weights(df, n_f)
    else:
        # lint: invariant-ok (per-shard weight-vector prep inside the SPMD
        # program; hoisting would add a host round-trip per dispatch)
        q_weight = idf_weights(df, n_scalar, compat_int_idf)

    def body(q, qw, c, *leaves):
        lay, base = _unpack_local(*leaves)
        scores = _local_scores(q, qw, lay, dblk=dblk, scoring=scoring,
                               n_f=n_f, k1=k1, b=b, hot_only=hot_only)
        return _gather_candidates(scores, c, base, dblk)

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(None, None), P(None), P(None, None))
        + _layout_specs_flat(tier_docs),
        out_specs=P(None, None),
        check_vma=False)
    return fn(q_terms, q_weight, cand, hot_rank, hot_tfs, tier_of, row_of,
              doc_len, doc_base, tier_docs, tier_tfs)


def sharded_tiered_scores_at(q_terms, layout: ShardedTieredLayout, df,
                             num_docs, cand, *, mesh,
                             scoring: str = "tfidf",
                             compat_int_idf: bool = False,
                             k1: float = 0.9, b: float = 0.4,
                             hot_only: bool = False):
    """Explain debug variant of sharded_tiered_topk: [B, C] f32 scores at
    global docnos `cand` instead of the merged top-k. Each shard runs the
    identical `_local_scores` accumulation, so the gathered value for a
    doc is bit-identical to the local score the production merge top-k'd
    (search/explain.py pins this)."""
    q_terms = replicated_global(q_terms, mesh)
    df = replicated_global(df, mesh)
    num_docs = replicated_global(np.int32(num_docs), mesh)
    cand = replicated_global(jnp.asarray(cand, jnp.int32), mesh)
    return _sharded_scores_at_jit(
        q_terms, df, num_docs, cand, layout.hot_rank, layout.hot_tfs,
        layout.tier_of, layout.row_of, layout.doc_len, layout.doc_base,
        layout.tier_docs, layout.tier_tfs, mesh=mesh, dblk=layout.dblk,
        scoring=scoring, compat_int_idf=compat_int_idf, k1=k1, b=b,
        hot_only=hot_only)


@partial(profiled_jit, static_argnames=("mesh", "dblk", "k1", "b"))
def _sharded_cosine_at_jit(q_terms, df, n_scalar, doc_norm, cand,
                           hot_rank, hot_tfs, tier_of, row_of, doc_len,
                           doc_base, tier_docs, tier_tfs, *, mesh, dblk,
                           k1, b):
    n_f = jnp.asarray(n_scalar, jnp.float32)
    idf = idf_weights(df, n_scalar)
    w_cos = idf * idf

    def body(q, w2, norm, c, *leaves):
        lay, base = _unpack_local(*leaves)
        # stage 2 of _sharded_rerank_jit verbatim: cosine scores over the
        # block, normalized, candidates assembled by psum
        s2 = _local_scores(q, w2, lay, dblk=dblk, scoring="tfidf",
                           n_f=n_f, k1=k1, b=b)
        s2 = s2 / jnp.maximum(norm.reshape(norm.shape[-1]), 1e-30)[None, :]
        return _gather_candidates(s2, c, base, dblk)

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(None, None), P(None), P(SHARD_AXIS, None),
                  P(None, None)) + _layout_specs_flat(tier_docs),
        out_specs=P(None, None),
        check_vma=False)
    return fn(q_terms, w_cos, doc_norm, cand, hot_rank, hot_tfs, tier_of,
              row_of, doc_len, doc_base, tier_docs, tier_tfs)


def sharded_tiered_cosine_at(q_terms, layout: ShardedTieredLayout, df,
                             num_docs, doc_norm, cand, *, mesh,
                             k1: float = 0.9, b: float = 0.4):
    """Explain debug variant of sharded_tiered_rerank's cosine stage:
    [B, C] per-candidate cosine scores in candidate order."""
    q_terms = replicated_global(q_terms, mesh)
    df = replicated_global(df, mesh)
    num_docs = replicated_global(np.int32(num_docs), mesh)
    cand = replicated_global(jnp.asarray(cand, jnp.int32), mesh)
    return _sharded_cosine_at_jit(
        q_terms, df, num_docs, doc_norm, cand, layout.hot_rank,
        layout.hot_tfs, layout.tier_of, layout.row_of, layout.doc_len,
        layout.doc_base, layout.tier_docs, layout.tier_tfs, mesh=mesh,
        dblk=layout.dblk, k1=k1, b=b)


@partial(profiled_jit,
         static_argnames=("mesh", "k", "candidates", "k1", "b",
                          "dblk"))
def _sharded_rerank_jit(q_terms, df, n_scalar, doc_norm, hot_rank, hot_tfs,
                        tier_of, row_of, doc_len, doc_base, tier_docs,
                        tier_tfs, *, mesh, dblk, k, candidates, k1, b):
    n_f = jnp.asarray(n_scalar, jnp.float32)
    # lint: invariant-ok (per-shard weight-vector prep inside the SPMD
    # program; hoisting would add a host round-trip per dispatch)
    w_bm25 = bm25_idf_weights(df, n_f)
    idf = idf_weights(df, n_scalar)
    w_cos = idf * idf

    def body(q, w1, w2, norm, *leaves):
        lay, base = _unpack_local(*leaves)
        # stage 1: BM25 candidate generation (distributed top-C merge)
        s1 = _local_scores(q, w1, lay, dblk=dblk, scoring="bm25",
                           n_f=n_f, k1=k1, b=b)
        _, cand = _merge_topk(s1, base, candidates)      # [B, C] global
        # stage 2: cosine TF-IDF, each device scoring its block then
        # contributing the candidates that live there (psum assembles —
        # every candidate belongs to exactly one device's block)
        s2 = _local_scores(q, w2, lay, dblk=dblk, scoring="tfidf",
                           n_f=n_f, k1=k1, b=b)
        s2 = s2 / jnp.maximum(norm.reshape(norm.shape[-1]), 1e-30)[None, :]
        cs = _gather_candidates(s2, cand, base, dblk)     # [B, C]
        return _topk_over_candidates(cs, cand, k)

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(None, None), P(None), P(None), P(SHARD_AXIS, None))
        + _layout_specs_flat(tier_docs),
        out_specs=(P(None, None), P(None, None)),
        check_vma=False)
    return fn(q_terms, w_bm25, w_cos, doc_norm, hot_rank, hot_tfs, tier_of,
              row_of, doc_len, doc_base, tier_docs, tier_tfs)


def sharded_tiered_rerank(q_terms, layout: ShardedTieredLayout, df,
                          num_docs, doc_norm, *, mesh, k: int = 10,
                          candidates: int = 1000,
                          k1: float = 0.9, b: float = 0.4):
    """Two-stage retrieval on the mesh: BM25 top-`candidates`, cosine
    TF-IDF rerank — same model as the single-device pipeline
    (ops/scoring.py::cosine_rerank_dense), both stages inside one SPMD
    program. `doc_norm` is the sharded [S, dblk+1] form of the global
    (1+ln tf)*idf doc norms (see shard_slices), already placed on the mesh
    (put_doc_sharded)."""
    q_terms = replicated_global(q_terms, mesh)
    df = replicated_global(df, mesh)
    num_docs = replicated_global(np.int32(num_docs), mesh)
    return _sharded_rerank_jit(
        q_terms, df, num_docs, doc_norm, layout.hot_rank, layout.hot_tfs,
        layout.tier_of, layout.row_of, layout.doc_len, layout.doc_base,
        layout.tier_docs, layout.tier_tfs, mesh=mesh, dblk=layout.dblk,
        k=k, candidates=candidates, k1=k1, b=b)
