"""SPMD inverted-index build: doc-sharded map, all_to_all shuffle, term-sharded
reduce — one jit-compiled program over a device mesh.

Reference mapping (SURVEY.md §2.5 table):
  Hadoop input splits -> mapper tasks   = doc-shard axis of the mesh
  hash partitioner over 10 reducers     = dest = term_id % num_shards
  sort/shuffle (HTTP)                   = jax.lax.all_to_all over ICI
  combiner (map-side pre-aggregation)   = per-device pre-group before routing
  MR counters / corpus size N           = jax.lax.psum
  part-NNNNN reducer outputs            = per-device term-shard postings

Static shapes: each device sends exactly `bucket_cap` (term, doc) slots to
each destination (MoE-style capacity). Overflowed pairs are counted (psum'd)
and surfaced so the host can retry with a bigger capacity — the moral
equivalent of a failed-task retry, but deterministic (SURVEY.md §5 failure
handling).
"""

from __future__ import annotations

import logging
import random
import time
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .. import faults
from ..obs import flight_dump
from ..obs import trace as obs_trace
from ..obs.progress import report_progress
from ..utils.report import recovery_counters

logger = logging.getLogger(__name__)

from ..ops.postings import (PAD_TERM, build_postings,
                            build_postings_packed,
                            reduce_weighted_postings, round_cap)
from .mesh import SHARD_AXIS, make_mesh, shard_map


def deal_occurrences(flat_term: np.ndarray, flat_doc: np.ndarray,
                     docnos: np.ndarray, num_shards: int,
                     granule: int = 1 << 14):
    """Deal flat occurrence columns into the mesh program's inputs:
    (term_ids [S, cap], doc_ids [S, cap], docs_per_shard [S]), where doc
    (docno - 1) % S owns each occurrence and cap is the largest shard's
    fill bucketed by `granule` (shared compiled shapes). THE dealing
    rule — the in-memory SPMD build and the streaming SPMD pass 2 both
    route through here, and their byte-identical-artifacts guarantee
    depends on the rule staying single-sourced."""
    s = num_shards
    doc_shard = (flat_doc - 1) % s
    fill = (int(np.bincount(doc_shard, minlength=s).max())
            if len(flat_term) else 1)
    cap = round_cap(fill, granule)
    t_arr = np.full((s, cap), PAD_TERM, np.int32)
    d_arr = np.zeros((s, cap), np.int32)
    for sh in range(s):
        sel = doc_shard == sh
        n = int(sel.sum())
        t_arr[sh, :n] = flat_term[sel]
        d_arr[sh, :n] = flat_doc[sel]
    dps = np.bincount((docnos - 1) % s, minlength=s).astype(np.int32)
    return t_arr, d_arr, dps


class ShardedPostings(NamedTuple):
    """Per-term-shard postings, one leaf row per mesh shard.

    All arrays carry a leading [num_shards] axis (sharded over the mesh):
    pair_term/pair_doc/pair_tf int32 [S, C]; df int32 [S, V] (only the
    shard's own terms nonzero); num_pairs int32 [S]; dropped int32 [S]
    (overflow counts, all equal after psum); num_docs int32 [S] (global N,
    all equal after psum).
    """

    pair_term: jax.Array
    pair_doc: jax.Array
    pair_tf: jax.Array
    df: jax.Array
    num_pairs: jax.Array
    dropped: jax.Array
    num_docs: jax.Array


def _route_and_build(term_ids, doc_ids, local_num_docs, *, num_shards: int,
                     vocab_size: int, bucket_cap: int, total_docs: int):
    """Per-device body under shard_map. term_ids/doc_ids: int32 [1, C]."""
    term_ids = term_ids.reshape(-1)
    doc_ids = doc_ids.reshape(-1)
    local_num_docs = local_num_docs.reshape(())
    c = term_ids.shape[0]

    # combiner: pre-group local (term, doc) pairs so each unique pair crosses
    # the interconnect once with an aggregated tf (reference combiner=reducer,
    # TermKGramDocIndexer.java:273)
    local = build_postings(term_ids, doc_ids, vocab_size=vocab_size,
                           num_docs=total_docs)
    g_term = local.pair_term
    g_doc = local.pair_doc
    g_tf = local.pair_tf
    g_valid = g_term != PAD_TERM
    g_dest = jnp.where(g_valid, g_term % num_shards, num_shards)

    # rank of each pair within its destination bucket
    order = jnp.argsort(g_dest, stable=True)
    d_sorted = g_dest[order]
    ranks_sorted = jnp.arange(c, dtype=jnp.int32) - jnp.searchsorted(
        d_sorted, d_sorted, side="left").astype(jnp.int32)
    rank = jnp.zeros((c,), jnp.int32).at[order].set(ranks_sorted)

    in_cap = g_valid & (rank < bucket_cap)
    dropped = jnp.sum(g_valid & ~in_cap).astype(jnp.int32)

    slot = jnp.where(in_cap, g_dest * bucket_cap + rank, num_shards * bucket_cap)
    send_term = jnp.full((num_shards * bucket_cap,), PAD_TERM, jnp.int32
                         ).at[slot].set(g_term, mode="drop")
    send_doc = jnp.zeros((num_shards * bucket_cap,), jnp.int32
                         ).at[slot].set(g_doc, mode="drop")
    send_tf = jnp.ones((num_shards * bucket_cap,), jnp.int32
                       ).at[slot].set(g_tf, mode="drop")

    # the shuffle: bucket b of device s -> device b
    recv_term = jax.lax.all_to_all(
        send_term.reshape(num_shards, bucket_cap), SHARD_AXIS, 0, 0, tiled=False)
    recv_doc = jax.lax.all_to_all(
        send_doc.reshape(num_shards, bucket_cap), SHARD_AXIS, 0, 0, tiled=False)
    recv_tf = jax.lax.all_to_all(
        send_tf.reshape(num_shards, bucket_cap), SHARD_AXIS, 0, 0, tiled=False)
    recv_term = recv_term.reshape(num_shards * bucket_cap)
    recv_doc = recv_doc.reshape(num_shards * bucket_cap)
    recv_tf = recv_tf.reshape(num_shards * bucket_cap)

    # term-shard reduce: merge partial tf postings from every doc shard
    r_term, r_doc, r_tf, df, num_pairs = reduce_weighted_postings(
        recv_term, recv_doc, recv_tf, vocab_size=vocab_size)

    # global counters over the mesh (reference MR counters / sentinel term)
    n_total = jax.lax.psum(local_num_docs, SHARD_AXIS)
    dropped_total = jax.lax.psum(dropped, SHARD_AXIS)

    return (r_term[None], r_doc[None], r_tf[None], df[None],
            num_pairs[None], dropped_total[None], n_total[None])


@partial(jax.jit, static_argnames=("num_shards", "vocab_size", "bucket_cap",
                                   "total_docs", "mesh"))
def _sharded_build_jit(term_ids, doc_ids, local_num_docs, *, mesh,
                       num_shards, vocab_size, bucket_cap, total_docs):
    fn = shard_map(
        partial(_route_and_build, num_shards=num_shards,
                vocab_size=vocab_size, bucket_cap=bucket_cap,
                total_docs=total_docs),
        mesh=mesh,
        in_specs=(P(SHARD_AXIS, None), P(SHARD_AXIS, None), P(SHARD_AXIS)),
        out_specs=(P(SHARD_AXIS, None),) * 3 + (P(SHARD_AXIS, None),)
        + (P(SHARD_AXIS),) * 3,
    )
    return fn(term_ids, doc_ids, local_num_docs)


def sharded_build_postings(
    term_ids: np.ndarray,     # int32 [S, C] per-doc-shard occurrences (padded)
    doc_ids: np.ndarray,      # int32 [S, C]
    docs_per_shard: np.ndarray,  # int32 [S]
    *,
    vocab_size: int,
    total_docs: int,
    mesh=None,
    bucket_cap: int | None = None,
    retry_policy: faults.RetryPolicy | None = None,
) -> ShardedPostings:
    """Run the SPMD build under a supervised capacity-retry policy.

    Each overflow re-dispatch doubles the bucket capacity — the moral
    equivalent of a failed-task retry, made deterministic — with the
    policy's jittered backoff between dispatches (an overflow on real
    hardware means re-running a collective program; hammering it
    back-to-back starves concurrent users of the chip). The attempt
    bound is the CAPACITY CEILING, not a fixed count: bucket_cap == C
    holds every pair a device could route to ONE destination, so growth
    beyond it is provably useless and exhaustion there raises a
    structured BuildError. (A fixed attempt count once stopped the
    doubling at c/2 on meshes with s > 16, failing feasible skewed
    distributions — the bound must track feasibility, which the ceiling
    does and a count does not.)"""
    s, c = term_ids.shape
    if mesh is None:
        mesh = make_mesh(s)
    if bucket_cap is None:
        # expected pairs per (device, dest) with 2x headroom, 128-aligned
        bucket_cap = max(128, int(2 * c / s) + 127 & ~127)
    policy = retry_policy or faults.OVERFLOW_RETRY
    rng = random.Random(policy.seed)
    attempt = 0
    while True:
        attempt += 1
        with obs_trace("build.shuffle", attempt=attempt,
                       bucket_cap=bucket_cap, shards=s):
            out = _sharded_build_jit(
                jnp.asarray(term_ids), jnp.asarray(doc_ids),
                jnp.asarray(docs_per_shard),
                mesh=mesh, num_shards=s, vocab_size=vocab_size,
                bucket_cap=bucket_cap, total_docs=total_docs)
        # JobTracker counter: bytes the all_to_all moved this dispatch
        # (3 int32 columns x S senders x S*cap slots each — the "shuffle
        # bytes" column of the reference pages). Reported to whatever
        # phase is current: this helper runs under "postings" in the
        # in-memory build and "pass2_combine" in the streaming/
        # multi-host builds.
        report_progress(None, shuffle_bytes=3 * 4 * s * s * bucket_cap,
                        shuffle_dispatches=1)
        result = ShardedPostings(*out)
        # dropped is psum'd (identical on every shard); read an addressable
        # shard so this also works on a multi-host mesh
        dropped = int(np.asarray(
            result.dropped.addressable_shards[0].data).ravel()[0])
        if faults.should_fire("shuffle_overflow") is not None:
            dropped = max(dropped, 1)
        if dropped == 0:
            return result
        if bucket_cap >= c:
            flight_dump("build_error", extra={
                "stage": "all_to_all_shuffle", "attempt": attempt,
                "bucket_cap": bucket_cap, "dropped": dropped})
            raise faults.BuildError(
                "all_to_all_shuffle", attempt,
                f"routing overflow persists at bucket_cap={bucket_cap} == "
                f"capacity {c} ({dropped} pairs dropped): every pair fits "
                "one destination bucket, so this is a routing bug, not "
                "skew")
        recovery_counters().incr("overflow_retries")
        logger.warning(
            "all_to_all overflow (%d pairs dropped) at bucket_cap=%d; "
            "re-dispatching at %d (attempt %d)", dropped, bucket_cap,
            min(bucket_cap * 2, c), attempt + 1)
        time.sleep(policy.delay_s(attempt, rng))
        bucket_cap = min(bucket_cap * 2, c)


class BucketPostings(NamedTuple):
    """Per-RADIX-BUCKET postings, one leaf row per mesh device (ISSUE
    11): row i is the complete, final reduce of bucket i's occurrence
    stream — unlike ShardedPostings there is no collective in the
    program, because a radix bucket's pairs already live wholly on the
    device that uploaded them. pair_term/pair_doc/pair_tf int32 [S, C];
    df int32 [S, V] (only the bucket's own terms nonzero); num_pairs
    int32 [S]."""

    pair_term: jax.Array
    pair_doc: jax.Array
    pair_tf: jax.Array
    df: jax.Array
    num_pairs: jax.Array


def _bucket_reduce(term_ids, docnos, lengths, *, vocab_size: int,
                   total_docs: int):
    """Per-device body under shard_map: one bucket's full local reduce
    (re-expand doc runs, sort, combine tfs, order postings) — the
    single-device combine verbatim, which is what makes the radix SPMD
    path's artifacts bit-identical to the single-device radix build."""
    p = build_postings_packed(
        term_ids.reshape(-1), docnos.reshape(-1), lengths.reshape(-1),
        vocab_size=vocab_size, num_docs=total_docs)
    return (p.pair_term[None], p.pair_doc[None], p.pair_tf[None],
            p.df[None], p.num_pairs[None])


def _radix_reduce_impl(term_ids, docnos, lengths, *, mesh,
                       vocab_size: int, total_docs: int):
    fn = shard_map(
        partial(_bucket_reduce, vocab_size=vocab_size,
                total_docs=total_docs),
        mesh=mesh,
        in_specs=(P(SHARD_AXIS, None),) * 3,
        out_specs=(P(SHARD_AXIS, None),) * 4 + (P(SHARD_AXIS),),
    )
    return fn(term_ids, docnos, lengths)


from ..obs.profiling import profiled_jit  # noqa: E402  (kernel deps above)

# two compiled entry points, chosen by backend: on TPU the occurrence
# upload is donated (the SNIPPETS pjit donation pattern — the input
# buffer is dead once the reduce consumes it, so XLA reuses its HBM
# pages for the output and peak memory stays ~one bucket); the CPU
# backend ignores donation with a warning per call, so it gets the
# undonated twin
_RADIX_REDUCE_DONATED = profiled_jit(
    _radix_reduce_impl, label="radix_bucket_reduce",
    static_argnames=("mesh", "vocab_size", "total_docs"),
    donate_argnums=(0, 1, 2))
_RADIX_REDUCE = profiled_jit(
    _radix_reduce_impl, label="radix_bucket_reduce",
    static_argnames=("mesh", "vocab_size", "total_docs"))


def radix_bucket_reduce(term_ids: np.ndarray, docnos: np.ndarray,
                        lengths: np.ndarray, *, vocab_size: int,
                        total_docs: int, mesh=None) -> BucketPostings:
    """Reduce S radix buckets, one per mesh device, in ONE dispatch.

    term_ids: uint16/int32 [S, C] PAD-padded occurrence term ids;
    docnos/lengths: int32 [S, D] run-packed documents (docno + run
    length, zero-padded). Row i's output is bucket i's final postings —
    embarrassingly parallel, no shuffle: the radix partition already
    routed every (term, doc) pair to exactly one bucket in pass 1."""
    s = term_ids.shape[0]
    if mesh is None:
        mesh = make_mesh(s)
    donate = all(d.platform == "tpu" for d in mesh.devices.flat)
    fn = _RADIX_REDUCE_DONATED if donate else _RADIX_REDUCE
    with obs_trace("build.radix", buckets=s,
                   occ_cap=int(term_ids.shape[1])):
        out = fn(jnp.asarray(term_ids), jnp.asarray(docnos),
                 jnp.asarray(lengths), mesh=mesh,
                 vocab_size=vocab_size, total_docs=total_docs)
    return BucketPostings(*out)
