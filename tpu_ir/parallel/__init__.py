from .mesh import SHARD_AXIS, make_mesh
from .sharded_build import ShardedPostings, sharded_build_postings
from .sharded_tiered import (
    ShardedTieredLayout,
    make_sharded_tiered,
    put_sharded,
    shard_slices,
    sharded_tiered_rerank,
    sharded_tiered_topk,
)

__all__ = [
    "SHARD_AXIS",
    "make_mesh",
    "ShardedPostings",
    "sharded_build_postings",
    "ShardedTieredLayout",
    "make_sharded_tiered",
    "put_sharded",
    "shard_slices",
    "sharded_tiered_rerank",
    "sharded_tiered_topk",
]
