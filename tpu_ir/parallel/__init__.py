from .mesh import SHARD_AXIS, make_mesh
from .sharded_build import ShardedPostings, sharded_build_postings
from .sharded_scoring import make_doc_blocks, sharded_tfidf_topk

__all__ = [
    "SHARD_AXIS",
    "make_mesh",
    "ShardedPostings",
    "sharded_build_postings",
    "make_doc_blocks",
    "sharded_tfidf_topk",
]
