"""CLI surface: index / search / inspect / expand.

Preserves the reference's command shapes (SURVEY.md §1 L5): the reference's
`hadoop jar cloud9.jar TermKGramDocIndexer k input output mapping` becomes
`tpu-ir index --k K CORPUS... INDEX_DIR`; the query REPL
(IntDocVectorsForwardIndex.java:243-322) becomes `tpu-ir search INDEX_DIR`
(interactive) or `--query/--queries-file` (batch); ReadSequenceFile's index
dumping becomes `tpu-ir inspect`.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time



def _add_backend_arg(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--backend", choices=["auto", "cpu", "tpu"], default="auto",
        help="device backend; 'cpu' is the reference's local mode equivalent")
    p.add_argument(
        "--profile", metavar="DIR", default=None,
        help="write a jax.profiler trace (view with tensorboard/xprof)")
    p.add_argument(
        "--faults", metavar="SPEC", default=None,
        help="install a deterministic fault-injection plan (tpu_ir.faults "
             "spec grammar, e.g. 'spill_write@pairs-:first@2'); equivalent "
             "to the TPU_IR_FAULTS env var")


# PJRT factory names known to front TPU hardware; anything else must
# additionally LOOK like a TPU (platform/device_kind) to be accepted
_TPU_PLUGIN_NAMES = ("tpu", "axon")


def _devices_look_tpu(devices) -> bool:
    d = devices[0]
    plat = (getattr(d, "platform", "") or "").lower()
    kind = (getattr(d, "device_kind", "") or "").lower()
    return "tpu" in plat or "tpu" in kind


def _apply_backend(args) -> None:
    if getattr(args, "faults", None):
        from . import faults

        faults.install(faults.parse_plan(args.faults))
    if args.backend == "auto":
        return
    # hard-pin: the environment may pre-set JAX_PLATFORMS (and a PJRT plugin
    # may have force-updated jax.config at interpreter start), so setting the
    # env var alone is not enough. Factories stay registered (the pin is the
    # platform list, so a later in-process call can still pick another
    # backend); already-initialized backends are cleared so the pin takes.
    import jax
    import jax._src.xla_bridge as xb

    def pin(name: str) -> None:
        os.environ["JAX_PLATFORMS"] = name
        jax.config.update("jax_platforms", name)
        if xb.backends_are_initialized():
            from jax.extend.backend import clear_backends

            clear_backends()

    if args.backend != "tpu":
        pin(args.backend)
        return
    # the chip may ride a plugin name (e.g. "axon"), and a registered
    # "tpu" factory can still fail to initialize (libtpu present, no
    # local device — jax raises even when the platform list has more
    # entries). Probe ALLOWLISTED TPU plugin names in order, canonical
    # "tpu" first. Other registered factories are probed last and
    # accepted only when their devices actually identify as TPUs
    # (platform/device_kind) — an unknown non-TPU plugin must be
    # REJECTED, not silently adopted as "the TPU" (ADVICE r5: the old
    # denylist accepted any future platform name it had never heard of,
    # the exact misconfiguration-masking this flag exists to prevent).
    allow = sorted((n for n in xb._backend_factories
                    if n in _TPU_PLUGIN_NAMES),
                   key=lambda n: n != "tpu")
    others = sorted(n for n in xb._backend_factories
                    if n not in _TPU_PLUGIN_NAMES
                    and n not in ("cpu", "cuda", "gpu", "rocm", "metal"))
    last_err: Exception | None = None
    rejected: list[str] = []
    for cand in allow + others:
        pin(cand)
        try:
            devices = jax.devices()
        except RuntimeError as e:
            last_err = e
            continue
        if cand in _TPU_PLUGIN_NAMES or _devices_look_tpu(devices):
            return
        rejected.append(
            f"{cand} (devices identify as "
            f"{getattr(devices[0], 'platform', '?')}/"
            f"{getattr(devices[0], 'device_kind', '?')}, not TPU)")
    raise ValueError(
        "--backend tpu: no TPU backend initialized (tried "
        f"{(allow + others) or 'no TPU-like factories'}; "
        f"rejected: {rejected or 'none'}; available: "
        f"{sorted(xb._backend_factories)}; last error: {last_err})")


class _MaybeProfile:
    """jax.profiler.trace wrapper (SURVEY.md §5: the JobTracker-page
    observability niche, filled with real device traces)."""

    def __init__(self, trace_dir: str | None):
        self._dir = trace_dir
        self._cm = None

    def __enter__(self):
        if self._dir:
            import jax

            self._cm = jax.profiler.trace(self._dir)
            self._cm.__enter__()
        return self

    def __exit__(self, *exc):
        if self._cm:
            self._cm.__exit__(*exc)
        return False


class _MaybeTrack:
    """Run the command under an embedded metrics/jobs HTTP server
    (`--track PORT` / serve-bench `--metrics-port PORT`): /jobs shows
    the live JobTracker-style progress of the build or soak, /metrics
    is scrapeable mid-run, and the server shuts down cleanly with the
    command (obs/server.py). Port 0 binds an ephemeral port; the chosen
    URL is announced on stderr either way."""

    def __init__(self, port: int | None):
        self._port = port
        self.server = None

    def __enter__(self):
        if self._port is not None:
            from .obs.server import start_server

            self.server = start_server(port=self._port)
            print(f"tpu-ir: serving live telemetry on {self.server.url} "
                  "(/metrics /healthz /jobs /flight)", file=sys.stderr)
        return self

    def __exit__(self, *exc):
        if self.server is not None:
            self.server.stop()
        return False


def cmd_index(args) -> int:
    _apply_backend(args)
    with _MaybeProfile(args.profile), _MaybeTrack(args.track):
        return _run_index(args)


def _run_index(args) -> int:
    # validate the user-supplied corpus paths up front: a missing corpus
    # is a usage error with a clean message, while a FileNotFoundError
    # raised DEEPER in the build (a temp/spill file that should exist)
    # is a real defect and must traceback, not masquerade as usage
    # (ADVICE r5 — cmd_index is deliberately not in _ARTIFACT_ENTRY_CMDS)
    missing = [p for p in args.corpus if not os.path.exists(p)]
    if missing:
        print(f"error: corpus path(s) not found: {', '.join(missing)}",
              file=sys.stderr)
        return 1
    if args.streaming:
        from .index.streaming import build_index_streaming

        meta = build_index_streaming(
            args.corpus, args.index_dir, k=args.k,
            chargram_ks=args.chargram_k, num_shards=args.shards,
            batch_docs=args.batch_docs,
            compute_chargrams=not args.no_chargrams,
            spmd_devices=args.spmd_devices,
            overwrite=args.overwrite, positions=args.positions,
            store=args.store, radix_buckets=args.radix_buckets,
            tokenize_procs=args.tokenize_procs)
    else:
        from .index import build_index

        meta = build_index(
            args.corpus, args.index_dir, k=args.k,
            chargram_ks=args.chargram_k, num_shards=args.shards,
            overwrite=args.overwrite,
            compute_chargrams=not args.no_chargrams,
            spmd_devices=args.spmd_devices, positions=args.positions)
    out = dict(meta.__dict__)
    if args.store:
        from .index import docstore as ds

        # the streaming build wrote the store from its pass-1 text
        # spills (no second corpus read); the in-memory build — and a
        # prior index being re-run with --store, or one whose store a
        # crash left bin/idx-inconsistent — pays the corpus pass.
        # consistent(), not available(): this command is the recovery
        # path the DocStore error message recommends, so it must
        # actually rebuild a broken store.
        out["docstore"] = (ds.stats(args.index_dir)
                          if ds.consistent(args.index_dir)
                          else ds.build_docstore(args.corpus,
                                                 args.index_dir))
    print(json.dumps(out))
    return 0


def cmd_search(args) -> int:
    _apply_backend(args)
    with _MaybeProfile(args.profile):
        return _run_search(args)


def _run_search(args) -> int:
    from .search import Scorer

    if args.snippets:
        # fail BEFORE loading/printing anything: without a usable
        # document store (missing OR bin/idx-inconsistent from a crash
        # window) every result row would otherwise die mid-print on the
        # DocStore ValueError (ADVICE r4)
        from .index import docstore as ds

        if not ds.consistent(args.index_dir):
            print("error: index has no usable document store; rebuild "
                  "with `tpu-ir index --store` to render snippets",
                  file=sys.stderr)
            return 1
    scorer = Scorer.load(args.index_dir, layout=args.layout,
                         compat_int_idf=args.compat)
    show_docids = not args.docnos

    def run_batch(queries: list[str], qids: list | None = None) -> None:
        # reference guard: only 1-2 word queries
        # (IntDocVectorsForwardIndex.java:292,297)
        skipped = ({q for q in queries if len(q.split()) > 2}
                   if args.compat else set())
        kept = [q for q in queries if q not in skipped]
        results = iter(scorer.search_batch(
            kept, k=args.k, scoring=args.scoring,
            return_docids=show_docids, rerank=args.rerank,
            prox=args.prox, phrase_slop=args.slop) if kept else [])
        if qids is None:
            qids = list(range(1, len(queries) + 1))
        for qid, q in zip(qids, queries):
            if args.trec_run is None:
                print(f"query: {q}")
            if q in skipped:
                if args.trec_run is None:
                    print("  (compat mode: queries are limited to 1-2 "
                          "words)")
                continue
            res = next(results)
            if args.trec_run is not None:
                # standard trec_eval run format:
                # qid Q0 docid rank score run-tag
                for rank, (key, score) in enumerate(res, 1):
                    print(f"{qid} Q0 {key} {rank} {score:.6f} "
                          f"{args.trec_run}")
                continue
            if not res:
                print("  (no matching documents)")
            for rank, (key, score) in enumerate(res, 1):
                print(f"  {rank:2d}. {key}\t{score:.6f}")
                if args.show_matches:
                    print(f"      {_format_matches(scorer, q, key, show_docids)}")
                if args.snippets:
                    print(f"      {scorer.snippet(q, key, is_docid=show_docids)}")

    if args.query:
        run_batch([args.query])
    elif args.topics:
        qids, queries = _read_trec_topics(args.topics)
        run_batch(queries, qids=qids)
    elif args.queries_file:
        with open(args.queries_file) as f:
            queries = [line.strip() for line in f if line.strip()]
        run_batch(queries)
    else:
        # interactive REPL (reference main loop); 'exit' quits like the
        # reference's exit command (IntDocVectorsForwardIndex.java:289)
        print(f"tpu-ir: {scorer.meta.num_docs} docs, "
              f"{scorer.meta.vocab_size} terms, k={scorer.meta.k}, "
              f"layout={scorer.layout}. Type a query, or 'exit'.",
              file=sys.stderr if args.trec_run is not None else sys.stdout)
        next_qid = 1  # running qid so --trec-run lines stay distinct
        # input()'s prompt goes to stdout, so it would corrupt piped
        # output (run files, `| head`); only prompt at a real terminal
        prompt = ("query> " if sys.stdin.isatty() and sys.stdout.isatty()
                  and args.trec_run is None else "")
        while True:
            try:
                line = input(prompt).strip()
            except EOFError:
                break
            if not line:
                continue
            if line == "exit":
                break
            run_batch([line], qids=[next_qid])
            next_qid += 1
    return 0


def _format_matches(scorer, query: str, key, key_is_docid: bool) -> str:
    """Per-hit match coordinates from the format-v2 position runs: each
    analyzed query term with its token positions in the document (the
    closest thing to snippets an index without stored text can offer —
    the coordinates address the analyzed token stream)."""
    docno = (scorer.mapping.get_docno(key) if key_is_docid else int(key))
    pidx = scorer._phrase_index()
    parts = []
    for t in dict.fromkeys(
            scorer._query_term_sequence(query.replace('"', ' '))):
        pos = pidx.positions(t, docno)
        if pos is not None:
            parts.append(f"{t}@{','.join(str(int(p)) for p in pos)}")
    return " ".join(parts) if parts else "(no positional matches)"


def _read_trec_topics(path: str) -> tuple[list[str], list[str]]:
    """Parse a TREC topics file: <top> records with <num> Number: NNN and
    <title> lines; returns (qids, title queries). Tolerates both the
    classic SGML shape (title text on the following lines until the next
    tag) and single-line <title>text</title>."""
    with open(path, encoding="utf-8", errors="replace") as f:
        text = f.read()
    qids: list[str] = []
    queries: list[str] = []
    for top in re.split(r"(?i)<top>", text)[1:]:
        num = re.search(r"(?i)<num>\s*(?:Number:)?\s*([^<\s][^<\n]*)", top)
        title = re.search(
            r"(?i)<title>\s*(?:Topic:)?\s*(.*?)\s*(?=<|\Z)", top, re.S)
        if not num or not title:
            continue
        q = " ".join(title.group(1).split())
        if q:
            qids.append(num.group(1).strip())
            queries.append(q)
    return qids, queries


def cmd_lint(args) -> int:
    """Static analysis over the package source (ISSUEs 6 + 14):
    jit-hazard, concurrency, contract, determinism/lowering, and
    shape-universe passes — pure AST, no JAX import, fast enough for a
    pre-commit hook (`--diff REF` restricts per-file findings to
    changed files; `--self-test` re-proves the rules against their
    seeded fixtures). Exit 0 clean / 1 findings / 2 usage error (the
    CI contract tests/test_lint.py pins)."""
    from .lint import Baseline, run_lint
    from .lint.concurrency import build_lock_report
    from .lint.core import RULES

    pkg_root = os.path.dirname(os.path.abspath(__file__))
    root = os.path.abspath(args.path) if args.path else pkg_root
    if not os.path.isdir(root):
        print(f"error: not a directory: {root}", file=sys.stderr)
        return 2
    rel_root = os.path.dirname(root)
    # the package's import name scopes the whole-package-only contracts
    # (declared-but-never-emitted, RUNBOOK table): linting an external
    # fixture dir must not compare IT against tpu_ir's declarations
    pkg_name = os.path.basename(root)

    if args.env_table:
        from .utils import envvars

        print(envvars.markdown_table())
        return 0
    if args.locks:
        from .lint import PackageIndex

        print(json.dumps(build_lock_report(
            PackageIndex(root, pkg_name=pkg_name, rel_root=rel_root)), indent=2))
        return 0
    if args.self_test:
        from .lint.selftest import FIXTURES, run_selftest

        failures = run_selftest()
        for line in failures:
            print(f"FAIL: {line}", file=sys.stderr)
        print(f"lint self-test: {len(FIXTURES) - len(failures)}/"
              f"{len(FIXTURES)} fixtures ok", file=sys.stderr)
        return 1 if failures else 0

    findings = run_lint(root, pkg_name=pkg_name, rel_root=rel_root)

    baseline_path = args.baseline
    if baseline_path is None:
        candidate = os.path.join(rel_root, "lint_baseline.json")
        if os.path.exists(candidate):
            baseline_path = candidate
    baseline = Baseline()
    if baseline_path is not None:
        try:
            baseline = Baseline.load(baseline_path)
        except FileNotFoundError:
            print(f"error: baseline not found: {baseline_path}",
                  file=sys.stderr)
            return 2
        except (ValueError, KeyError) as e:
            print(f"error: unreadable baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2

    if args.fix_baseline:
        out_path = baseline_path or os.path.join(rel_root,
                                                 "lint_baseline.json")
        with open(out_path, "w", encoding="utf-8") as f:
            f.write(Baseline.render(findings, baseline))
        print(f"wrote {out_path} ({len(findings)} finding(s) "
              "grandfathered — review the reasons before merging)",
              file=sys.stderr)
        return 0

    fresh, stale = baseline.filter(findings)

    if args.diff is not None:
        # pre-commit mode: per-file REPORTING restricts to files changed
        # vs the git ref; package-level contracts (TPU30x registry
        # drift, TPU50x shape universe) stay whole-package — they can
        # break through ANY file. Applied after baseline filtering (and
        # never to --fix-baseline, which always rewrites from the FULL
        # finding set), so out-of-scope baseline entries are neither
        # reported stale nor dropped from a rewritten baseline.
        import subprocess

        from .lint.core import PACKAGE_LEVEL_RULES

        try:
            # --relative: paths come back relative to rel_root, the
            # same space findings' `file` fields live in (the package
            # may sit below the git top-level)
            res = subprocess.run(
                ["git", "-C", rel_root, "diff", "--name-only",
                 "--relative", args.diff, "--"],
                capture_output=True, text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired) as e:
            print(f"error: --diff needs git: {e}", file=sys.stderr)
            return 2
        if res.returncode != 0:
            print(f"error: git diff {args.diff} failed: "
                  f"{res.stderr.strip()}", file=sys.stderr)
            return 2
        changed = {ln.strip() for ln in res.stdout.splitlines()
                   if ln.strip()}
        fresh = [f for f in fresh
                 if f.rule.startswith(PACKAGE_LEVEL_RULES)
                 or f.file in changed]
        stale = []   # out-of-scope entries are not "no longer occurs"

    if args.json:
        print(json.dumps({
            "findings": [f.to_dict() for f in fresh],
            "baselined": len(findings) - len(fresh),
            "stale_baseline": stale,
            "rules": {r: {"severity": sev, "doc": doc}
                      for r, (sev, doc) in RULES.items()} if args.rules
            else None,
        }, indent=2))
    else:
        for f in fresh:
            print(f)
        for e in stale:
            print(f"note: stale baseline entry (finding no longer "
                  f"occurs): {e['rule']} {e['file']}: {e['message']}",
                  file=sys.stderr)
        summary = (f"{len(fresh)} finding(s), "
                   f"{len(findings) - len(fresh)} baselined, "
                   f"{len(stale)} stale baseline entr(y/ies)")
        print(("FAIL: " if fresh else "ok: ") + summary, file=sys.stderr)
    return 1 if fresh else 0


def cmd_inspect(args) -> int:
    # artifact reading only — no jax backend needed
    from .collection import Vocab
    from .index import format as fmt

    # generic artifact dump (ReadSequenceFile generality): any FILE the
    # framework writes, a serving-cache dir, or a spill dir — everything
    # that is not a built index dir (index/artifacts.py)
    if not (os.path.isdir(args.index_dir)
            and fmt.artifact_exists(args.index_dir, fmt.METADATA)):
        from .index.artifacts import inspect_path

        try:
            for line in inspect_path(args.index_dir, n=args.n):
                print(line)
        except FileNotFoundError:
            print(f"no such artifact: {args.index_dir}", file=sys.stderr)
            return 1
        return 0

    if args.term is not None:
        # per-term random access through dictionary.tsv (the reference
        # getValue seek path, IntDocVectorsForwardIndex.java:148-184)
        from .index.dictionary import lookup_term

        hits = lookup_term(args.index_dir, args.term)
        if not hits:
            print(f"term {args.term!r} not in dictionary", file=sys.stderr)
            return 1
        for tp in hits:
            posts = [tuple(p) for p in tp.postings[: args.postings].tolist()]
            print(f"part-{tp.shard:05d}@{tp.offset}\t{tp.term}\tdf={tp.df}"
                  f"\t{posts}")
        return 0

    meta = fmt.IndexMetadata.load(args.index_dir)
    print(json.dumps(meta.__dict__))
    vocab = Vocab.load(os.path.join(args.index_dir, fmt.VOCAB))
    shown = 0
    for s in range(meta.num_shards):
        if shown >= args.n:
            break
        z = fmt.load_shard(args.index_dir, s)
        for i, tid in enumerate(z["term_ids"]):
            if shown >= args.n:
                break
            lo, hi = z["indptr"][i], z["indptr"][i + 1]
            posts = list(zip(z["pair_doc"][lo:hi].tolist(),
                             z["pair_tf"][lo:hi].tolist()))
            print(f"part-{s:05d}\t{vocab.term(int(tid))}\tdf={int(z['df'][i])}"
                  f"\t{posts[: args.postings]}")
            shown += 1
    return 0


def cmd_verify(args) -> int:
    from .index import segments as seg
    from .index.verify import verify_index, verify_live

    if seg.is_live(args.index_dir):
        print(json.dumps(verify_live(args.index_dir)))
    else:
        print(json.dumps(verify_index(args.index_dir)))
    return 0


def cmd_ingest(args) -> int:
    """The live-index write surface (ISSUE 12; index/ingest.py):
    `--init` creates a live dir; `--add`/`--update` feed TREC corpora
    through the IngestWriter (buffer -> delta segments + tombstones ->
    committed generations), `--delete` tombstones docids, `--merge`
    runs one tiered-merge step, `--compact` folds everything into one
    canonical servable segment, `--gc` prunes old generations. One
    JSON summary on stdout; serving picks up new generations via
    `reload_generation` / POST /rpc/reload (RUNBOOK §19)."""
    _apply_backend(args)
    from .index import segments as seg
    from .index.ingest import IngestWriter, ingest_corpus

    if args.swap_bench:
        from .obs.bench_check import append_history_row
        from .serving.generation import swap_microbench

        report = swap_microbench(args.live_dir)
        import jax

        row = {
            "config": "ingest_swap",
            "backend": jax.default_backend(),
            "num_docs": report["num_docs_b"],
            "swap_gap_ms": report["swap_gap_ms"],
            "swap_staleness_ms": report["swap_staleness_ms"],
            "swap_wall_s": report["swap_wall_s"],
        }
        report["history"] = append_history_row(row)
        report["history_row"] = row
        print(json.dumps(report, sort_keys=True))
        return 0
    if args.soak_bench:
        from .obs.bench_check import append_history_row
        from .serving.soak import run_ingest_soak

        report = run_ingest_soak(args.live_dir)
        import jax

        row = {
            "config": "ingest_soak",
            "backend": jax.default_backend(),
            "docs": report["docs"],
            "kills": report["kills"],
            "swaps": report["swaps"],
            "ingest_docs_per_s": report["ingest_docs_per_s"],
            "freshness_lag_ms": report["freshness_lag_ms"],
        }
        report["history"] = append_history_row(row)
        report["history_row"] = row
        print(json.dumps(report, sort_keys=True))
        return 0
    if args.init and not seg.is_live(args.live_dir):
        seg.LiveIndex.create(args.live_dir, k=args.k,
                             num_shards=args.shards,
                             chargram_ks=args.chargram_k)
    missing = [p for p in args.add + args.update
               if not os.path.exists(p)]
    if missing:
        print(f"error: corpus path(s) not found: {', '.join(missing)}",
              file=sys.stderr)
        return 1
    writer = IngestWriter(args.live_dir, buffer_docs=args.buffer_docs,
                          auto_merge=not args.no_auto_merge)
    added = sum(ingest_corpus(writer, p) for p in args.add)
    updated = sum(ingest_corpus(writer, p, update=True)
                  for p in args.update)
    deleted = sum(bool(writer.delete(d)) for d in args.delete)
    # compact/merge BEFORE close: close releases the WAL handle and the
    # writer lease, and a merge commit belongs inside the owned window
    if args.compact:
        writer.compact_all()
    elif args.merge:
        writer.flush()
        writer.maybe_merge()
    writer.close()
    live = writer.live
    out = {
        "live_dir": os.path.abspath(args.live_dir),
        "generation": live.current_gen(),
        "added": added, "updated": updated, "deleted": deleted,
        **live.doc_counts(),
        "segments": live.manifest()["segments"],
    }
    if args.gc:
        out["gc"] = live.gc()
    print(json.dumps(out, sort_keys=True))
    return 0


def cmd_backup(args) -> int:
    """Disaster-recovery surface (ISSUE 17; index/backup.py): snapshot
    a live dir's current generation — hardlinks where the filesystem
    allows, so an immutable segment costs no new bytes — including the
    WAL tail (acknowledged-but-unflushed writes restore via the
    ordinary replay path). `--restore` materializes a snapshot into a
    fresh dir and PROVES it with verify_live before reporting success
    (RUNBOOK §23's recipe)."""
    from .index.backup import backup_live, restore_live

    if args.restore:
        out = restore_live(args.src, args.dest)
    else:
        out = backup_live(args.src, args.dest)
    print(json.dumps(out, sort_keys=True))
    return 0


def cmd_generations(args) -> int:
    """List a live index's generation chain (ISSUE 12): per generation
    the segment set, doc counts, tombstones and whether it is directly
    servable — the operator view behind `reload_generation`. `--gc`
    prunes manifests/segments past TPU_IR_INGEST_KEEP_GENERATIONS."""
    from .index import segments as seg

    live = seg.LiveIndex.open(args.live_dir)
    gens = live.generations()
    if args.n:
        gens = gens[-args.n:]
    entries = []
    for g in gens:
        m = live.manifest(g)
        tombs = sum(len(t) for t in m.get("tombstones", {}).values())
        entries.append({
            "gen": g,
            "parent": m.get("parent"),
            "segments": m["segments"],
            "docs": sum(m.get("docs", {}).values()) - tombs,
            "tombstones": tombs,
            "servable": len(m["segments"]) == 1
            and not m.get("tombstones"),
            "note": m.get("note", ""),
            "created": m.get("created"),
        })
    out = {"live_dir": os.path.abspath(args.live_dir),
           "current": live.current_gen(),
           "generations": entries}
    if args.gc:
        out["gc"] = live.gc()
    print(json.dumps(out, sort_keys=True))
    return 0


def cmd_migrate_index(args) -> int:
    """Convert a built index's part shards between artifact formats in
    place (v1 npz <-> v2 arenas <-> v3 compressed; index/migrate.py):
    verify-while-read from the old copies, atomic rename per shard,
    checksums re-recorded, metadata.format_version stamped last.
    Idempotent — re-running finishes an interrupted migration.
    `--compress` / `--decompress` are the v3 spellings (RUNBOOK §26)."""
    from .index.migrate import migrate_index

    to = args.to
    if args.compress and args.decompress:
        print(json.dumps({"error": "--compress and --decompress are "
                                   "mutually exclusive"}))
        return 2
    if args.compress:
        to = 3
    elif args.decompress:
        to = 2
    print(json.dumps(migrate_index(args.index_dir, to_version=to,
                                   add_bounds=args.add_bounds,
                                   tf_dtype=args.tf_dtype)))
    return 0


def cmd_warm(args) -> int:
    """Prebuild the serving cache at deploy time instead of on the first
    query: one cold Scorer.load builds + persists the tiered layout, df
    and rerank norms (search/layout.py), so every later process start is
    the ~seconds fast path. No reference analog (its engine had no
    serving state to warm); this is the operational complement of the
    serving-cache design."""
    import time

    _apply_backend(args)
    from .search import Scorer

    start_wall = time.time()
    t0 = time.perf_counter()
    scorer = Scorer.load(args.index_dir, layout=args.layout)
    build_s = time.perf_counter() - t0
    if scorer.layout == "sharded":
        import jax

        cache_name = f"serving-sharded-{len(jax.devices())}"
    else:
        cache_name = "serving-tiered"  # dense layouts have no cache
    cache_dir = os.path.join(args.index_dir, cache_name)
    mtime = os.path.getmtime(cache_dir) if os.path.isdir(cache_dir) else 0
    t0 = time.perf_counter()
    warm = Scorer.load(args.index_dir, layout=args.layout)
    warm_s = time.perf_counter() - t0
    print(json.dumps({
        "layout": scorer.layout,
        "cache_dir": cache_name,
        # written BY THIS RUN (dir mtime after this command started), not
        # merely present from an earlier warm
        "cache_written": mtime >= start_wall - 1.0,
        "cold_load_s": round(build_s, 2),
        "warm_load_s": round(warm_s, 2),
        "warm_skips_shards": warm._pairs_cols is None,
    }))
    return 0


def cmd_stats(args) -> int:
    """Operator scrape surface: the process-wide recovery and serving
    counters, the fault-injection fire counts (the registry's fault.*
    ledger: sites that fired, regardless of which plan was installed)
    and the latency histogram summaries, one JSON object. Counters are
    per-process — meaningful from a serving process (serve-bench, a
    REPL, an embedding application), and all-zero from a fresh CLI
    invocation; the output SHAPE is the contract (a strict superset of
    the PR 2 shape; tests pin it). `--reset` reads-and-zeroes
    atomically, so repeated scrapes in one process report per-interval
    numbers with no event lost between read and reset."""
    from . import obs

    # ONE atomic snapshot feeds every section (with --reset, the
    # registry's read-and-zero guarantees an event lands in exactly one
    # interval): the recovery/serving sections are the counter prefixes
    # the deprecated aliases view, and fault_injection is the registry's
    # fault.* ledger (sites that actually fired — so it resets in step
    # with everything else, instead of the installed plan's lifetime
    # counts drifting against a per-interval scrape)
    if args.cluster:
        # same sections, cluster totals: the spooled per-process
        # snapshots merged (see cmd_metrics --cluster)
        from .obs import aggregate

        d = getattr(args, "telemetry_dir", None) or aggregate.spool_dir()
        snaps = aggregate.read_spool(d) if d else []
        if not snaps:
            print("error: --cluster needs spooled telemetry "
                  "(TPU_IR_TELEMETRY_DIR / --telemetry-dir)",
                  file=sys.stderr)
            return 1
        snap = aggregate.merge_snapshots(snaps)
        extra = {"processes": snap["processes"],
                 "per_process": snap["per_process"]}
    else:
        snap = obs.get_registry().snapshot(reset=args.reset)
        extra = {}

    def section(prefix: str) -> dict:
        n = len(prefix)
        return {k[n:]: v for k, v in snap["counters"].items()
                if k.startswith(prefix)}

    print(json.dumps({
        "recovery": section("recovery."),
        "serving": section("serving."),
        "fault_injection": {k: v for k, v in section("fault.").items()
                            if v},
        # dynamic pruning (ISSUE 13): the scheduled-skip raw terms and
        # the block-max mask ledger, full names (two namespaces share
        # the section, so no prefix is stripped)
        "pruning": {k: v for k, v in snap["counters"].items()
                    if k.startswith(("prune.", "blockmax."))},
        "histograms": snap["histograms"],
        **extra,
    }, sort_keys=True))
    return 0


def cmd_metrics(args) -> int:
    """The unified telemetry scrape: the whole TelemetryRegistry —
    every counter namespace (recovery.*, serving.*, fault.*) and every
    latency histogram — as one JSON object, or with `--prom` as
    Prometheus text exposition (counters as a labeled family,
    histograms in native cumulative-bucket form) for direct scraping.
    `--reset` zeroes the registry after reading."""
    from . import obs

    if args.cluster:
        # cluster view: every spooled process snapshot (newest per
        # run_id, TPU_IR_TELEMETRY_DIR / --telemetry-dir) merged —
        # counter totals are sums, histogram buckets add exactly.
        # A fresh CLI process's own (empty) registry is NOT folded in.
        from .obs import aggregate

        d = args.telemetry_dir or aggregate.spool_dir()
        if not d:
            print("error: --cluster needs TPU_IR_TELEMETRY_DIR or "
                  "--telemetry-dir", file=sys.stderr)
            return 1
        snaps = aggregate.read_spool(d)
        if not snaps:
            print(f"error: no spooled telemetry under {d}",
                  file=sys.stderr)
            return 1
        print(json.dumps(aggregate.merge_snapshots(snaps),
                         sort_keys=True))
        return 0
    reg = obs.get_registry()
    if args.prom:
        sys.stdout.write(reg.prometheus_text(reset=args.reset))
    else:
        print(json.dumps(reg.snapshot(reset=args.reset), sort_keys=True))
    return 0


def cmd_profile(args) -> int:
    """The device-cost profiling view (obs/profiling.py): per-signature
    compile counts with wall time and cost_analysis FLOPs/bytes, the
    dispatch time split (trace / compile / device), memory gauges and
    the recompile window, one JSON object. Per-process like `tpu-ir
    stats` — meaningful from a serving/bench process or the /profile
    endpoint of a tracked run; empty (the SHAPE is the contract) from a
    fresh CLI invocation."""
    from .obs.profiling import profile_report

    print(json.dumps(profile_report(), sort_keys=True, default=repr))
    return 0


def cmd_querylog(args) -> int:
    """Dump the sampled query log (obs/querylog.py): config + counters
    header, the ring entries, and the slow-query captures (span tree +
    explain for trapped offenders), one JSON object. Per-process like
    `tpu-ir stats` — meaningful from a serving/bench process or the
    /querylog endpoint of a tracked run; empty (the SHAPE is the
    contract) from a fresh CLI invocation."""
    from .obs import querylog

    out = dict(querylog.summary())
    if not args.slow:
        out["entries"] = querylog.recent(args.n)
    out["slow_entries"] = querylog.slow_recent(args.n)
    if getattr(args, "trace", None):
        # the distributed-trace join (ISSUE 18): only the entries this
        # trace id produced — the querylog face of `tpu-ir trace <id>`
        out["trace_filter"] = args.trace
        for key in ("entries", "slow_entries"):
            if key in out:
                out[key] = [e for e in out[key]
                            if e.get("trace_id") == args.trace]
    print(json.dumps(out, sort_keys=True, default=repr))
    return 0


def cmd_trace(args) -> int:
    """The distributed-trace surface (obs/disttrace.py). With no id:
    list every trace id visible here — the in-process store plus the
    span spool under TPU_IR_TELEMETRY_DIR (the post-mortem path: every
    process exported its kept span batches there). With an id: stitch
    the cross-process waterfall and render it — indented spans on a
    shared timeline, each attempt marked with the verdict the router
    recorded (won / lost / failed / cancelled / deadline). `--json`
    prints the stitched structure instead."""
    from .obs import disttrace
    from .obs.aggregate import read_span_spool

    if not args.trace_id:
        ids = set(disttrace.trace_ids())
        for rec in read_span_spool():
            tid = rec.get("trace_id")
            if tid:
                ids.add(tid)
        print(json.dumps({"traces": sorted(ids)}))
        return 0
    st = disttrace.stitch(args.trace_id)
    if st is None:
        print(json.dumps({"error": "unknown_trace",
                          "trace_id": args.trace_id}))
        return 1
    if args.json:
        print(json.dumps(st, sort_keys=True, default=repr))
        return 0
    _print_trace_waterfall(st)
    return 0


def _print_trace_waterfall(st: dict) -> None:
    """ASCII waterfall of one stitched trace: the jobdetails.jsp of the
    distributed tier, for terminals."""
    t0 = st["start_ms"]
    total = max(st["dur_ms"], 1e-9)
    width = 40
    print(f"trace {st['trace_id']}  spans={st['span_count']}  "
          f"dur={st['dur_ms']}ms  services={','.join(st['services'])}")

    def walk(node: dict, depth: int) -> None:
        off = max(node.get("start_ms", t0) - t0, 0.0)
        dur = node.get("dur_ms", 0.0)
        lo = min(int(width * off / total), width - 1)
        ln = max(1, min(int(round(width * dur / total)), width - lo))
        bar = " " * lo + "#" * ln
        a = node.get("attrs", {})
        mark = ""
        if a.get("outcome"):
            mark = f" [{a['outcome']}{'+hedge' if a.get('hedge') else ''}]"
        if node.get("error"):
            mark += " [error]"
        label = "  " * depth + node.get("name", "?")
        print(f"{label:<34.34} |{bar:<{width}}| {off:9.2f}ms "
              f"{dur:8.2f}ms  {node.get('service', '?')}{mark}")
        for c in node.get("children", ()):
            walk(c, depth + 1)

    for r in st["roots"]:
        walk(r, 0)


def cmd_doctor(args) -> int:
    """Index health report (index/doctor.py): df distribution and
    posting-list skew, per-shard term/postings balance, the EXACT
    hot-strip/tier occupancy serving will use, arena section sizes and
    serving-cache contents, plus heuristic warnings. Always exits 0 on
    a readable index — a health report, not a gate; the `warnings`
    list is the advisory surface."""
    from .index.doctor import doctor_report

    report = doctor_report(args.index_dir, top_terms=args.top)
    print(json.dumps(report, sort_keys=True))
    for w in report["warnings"]:
        print(f"doctor: warning: {w}", file=sys.stderr)
    return 0


def cmd_bench_check(args) -> int:
    """The BENCH_HISTORY.jsonl regression sentry (obs/bench_check.py):
    compare the newest row against the trailing-window median of its
    comparable predecessors and exit non-zero on a breach — the bench
    trajectory as an enforced contract instead of an append-only log.
    Exit 0 pass, 1 breach, 2 insufficient history; `--self-test` (the
    tier-1 gate) treats insufficient history as a clean skip."""
    from .obs.bench_check import run_check

    rc, report = run_check(
        args.history, window=args.window, min_rows=args.min_rows,
        tolerance=args.tolerance, self_test=args.self_test)
    print(json.dumps(report, sort_keys=True))
    if report.get("status") == "breach":
        for b in report.get("breaches", []):
            print(f"bench-check: {b['metric']} = {b['value']} is worse "
                  f"than the window median {b['median']} "
                  f"({b['direction']} is better)", file=sys.stderr)
    return rc


def cmd_trace_dump(args) -> int:
    """Dump the flight-recorder state on demand: the recent-trace ring
    (per-request / per-build span trees) plus a registry snapshot, as
    JSONL to stdout or `--out FILE` — the exact artifact shape an
    invariant breach writes automatically (header line included, via
    the shared recorder serializer), produced by an operator instead of
    a failure."""
    from .obs.recorder import artifact_lines

    lines = artifact_lines("manual_trace_dump")
    text = "\n".join(lines) + "\n"
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        # lines minus the header and telemetry records = trace count
        print(json.dumps({"traces": len(lines) - 2, "out": args.out}))
    else:
        sys.stdout.write(text)
    return 0


def _serve_sweep(args, scorer, levels: list) -> int:
    """The serve-bench concurrency-sweep mode (ISSUE 9): measure, print
    the full per-level report, append the sentry summary row."""
    import jax

    from .obs.bench_check import append_history_row
    from .serving import run_concurrency_sweep

    coalesce = args.coalesce != "off"  # sweep default: coalescing ON
    with _MaybeTrack(args.metrics_port) as track:
        report = run_concurrency_sweep(
            scorer, levels=tuple(levels), queries_per_level=args.queries,
            seed=args.seed, coalesce=coalesce, deadline_s=args.deadline)
        if track.server is not None:
            report["metrics_url"] = track.server.url
    # the sentry summary row: the largest swept level is the headline
    # (batched_* — by concurrency, NOT list order: `--concurrency
    # 32,8,1` must not trend solo latency as batched throughput);
    # level 1 — when swept — guards the solo path. The config key
    # carries the sweep shape + corpus size so bench-check never
    # medians an 8-client toy sweep against a 64-client 1M-doc one.
    top = max(report["levels"], key=lambda lv: lv["concurrency"])
    solo = next((lv for lv in report["levels"]
                 if lv["concurrency"] == 1), None)
    # coalesce-off A/B runs and custom deadlines are structurally
    # different regimes — they get their own comparability group
    # instead of dragging (or breaching) the default sweep's medians
    config_key = f"serve_sweep-{scorer.meta.num_docs}d-c{top['concurrency']}"
    if not coalesce:
        config_key += "-nocoalesce"
    if args.deadline is not None:
        config_key += f"-dl{args.deadline:g}"
    row = {
        "config": config_key,
        "backend": jax.default_backend(),
        "num_docs": scorer.meta.num_docs,
        "coalesce": coalesce,
        "scoring": report["scoring"],
        "concurrency": top["concurrency"],
        "levels": [lv["concurrency"] for lv in report["levels"]],
        "solo_rtt_ms": report["solo_rtt_ms"],
        "batched_qps": top["qps"],
        "batched_p50_ms": top["p50_ms"],
        "batched_p99_ms": top["p99_ms"],
        "batch_occupancy_mean": top["occupancy_mean"],
        "recompiles": sum(lv["recompiles"] for lv in report["levels"]),
    }
    if solo is not None:
        row["solo_p50_ms"] = solo["p50_ms"]
        row["solo_qps"] = solo["qps"]
    report["history"] = append_history_row(row)
    report["history_row"] = row
    print(json.dumps(report, sort_keys=True, default=repr))
    return 0 if all(lv["errors"] == 0 for lv in report["levels"]) else 1


def _workload_specs(args) -> list:
    """The serve-bench workload plan: one (label, spec) per run. The
    uniform workload is one run (spec None — the legacy seeded mixed
    draw); `--workload zipf --skew S[,S...]` is one run PER skew level
    (skew 0 = the uniform-control shape through the same generator, so
    the per-skew rows stay comparable)."""
    from .utils import envvars

    kind = args.workload or envvars.get_choice("TPU_IR_WORKLOAD")
    if kind == "uniform":
        return [("uniform", None)]
    if args.skew is None:
        skews = [envvars.get_float("TPU_IR_WORKLOAD_SKEW")]
    else:
        skews = [float(p) for p in str(args.skew).split(",") if p.strip()]
        if not skews or any(s < 0 for s in skews):
            raise ValueError(
                f"--skew {args.skew!r}: expected a non-negative number "
                "or comma list like 0,0.7,1.1")
    return [(f"zipf{s:g}", {"kind": "zipf", "skew": s,
                            "burst": args.burst})
            for s in skews]


def _serve_routed(args) -> int:
    """The serve-bench scatter-gather mode (ISSUE 10 + 15): spawn the
    S x R worker topology, drive the routed (optionally chaos) soak
    through the hedging router — once per workload skew level — print
    the invariant report(s), and append one routed_* sentry summary row
    per level to BENCH_HISTORY.jsonl where `tpu-ir bench-check` gates
    it (direction-aware; cache_hit_fraction / routed_qps /
    routed_p99_ms recorded per skew).

    `--autoscale` (ISSUE 16) makes the topology ELASTIC: the soak runs
    with the closed-loop autoscaler (serving/autoscale.py — grow one
    warm replica per shard on sustained pressure, drain-not-drop retire
    on sustained idleness), then a STATIC control run at the autoscaled
    run's mean active replica count, and the history row records
    scale_events / burst_p99_ms / overprovision_fraction next to the
    control's burst p99 — the measured claim that elasticity buys burst
    latency without buying idle replicas."""
    import jax

    from .obs.bench_check import append_history_row
    from .serving import run_distributed_soak

    if args.shards < 1 or args.replicas < 1:
        print("--shards and --replicas must be >= 1", file=sys.stderr)
        return 2
    layout = "sparse" if args.layout == "auto" else args.layout
    if layout == "sharded":
        print("--shards mode runs one single-device scorer per worker; "
              "use --layout sparse or dense", file=sys.stderr)
        return 2
    try:
        specs = _workload_specs(args)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    from .serving.result_cache import resolve_capacity

    cache_n = resolve_capacity(args.cache)
    reports = []
    ok = True
    pacing_kw = ({} if args.pacing is None
                 else {"pacing_s": args.pacing})
    re_cfg = None
    if args.autoscale:
        # ONE explicit config shared by the reactive and forecast arms
        # (the forecast arm adds only the third signal) — the A/B must
        # differ in exactly one bit
        from .serving.autoscale import AutoscaleConfig

        re_cfg = AutoscaleConfig(
            min_replicas=args.replicas,
            max_replicas=args.replicas + 1,
            cooldown_s=0.5, up_occupancy=0.45,
            down_occupancy=0.1, sustain_up=5,
            sustain_down=50, drain_timeout_s=15.0)
    with _MaybeTrack(args.metrics_port) as track:
        for label, spec in specs:
            report = run_distributed_soak(
                args.index_dir, shards=args.shards,
                replicas=args.replicas,
                threads=args.threads, queries=args.queries,
                seed=args.seed,
                layout=layout, chaos=args.chaos,
                worker_deadline_s=(1.0 if args.deadline is None
                                   else args.deadline),
                timeout_s=args.timeout, flight_dir=args.flight_dir,
                workload=spec, cache_entries=args.cache,
                autoscale=re_cfg if args.autoscale else False,
                **pacing_kw)
            if track.server is not None:
                report["metrics_url"] = track.server.url
            static = None
            forecast = None
            if args.autoscale:
                # the predictive A/B arm (ISSUE 19): same topology,
                # same workload, same seed — but the autoscaler's
                # THIRD signal armed. The soak's controller drives the
                # telemetry time machine's diurnal fit over the
                # occupancy series; forecast_occupancy (predicted
                # occupancy --lead seconds ahead) arms scale-up, so
                # growth starts before the burst crest instead of
                # after the queue builds. The row records each arm's
                # measured lead (first_peak - first_up, positive =
                # fired early) and burst p99 side by side.
                import dataclasses

                fc_cfg = dataclasses.replace(
                    re_cfg, forecast_up=re_cfg.up_occupancy,
                    forecast_lead_s=args.lead)
                forecast = run_distributed_soak(
                    args.index_dir, shards=args.shards,
                    replicas=args.replicas,
                    threads=args.threads, queries=args.queries,
                    seed=args.seed,
                    layout=layout, chaos=args.chaos,
                    worker_deadline_s=(1.0 if args.deadline is None
                                       else args.deadline),
                    timeout_s=args.timeout,
                    flight_dir=args.flight_dir,
                    workload=spec, cache_entries=args.cache,
                    autoscale=fc_cfg, **pacing_kw)
                report["forecast_arm"] = {
                    "burst_p99_ms": forecast["burst_p99_ms"],
                    "served": forecast["served"],
                    "shed": forecast["shed"],
                    "errors": forecast["errors"],
                    "scale": {k: forecast["scale"].get(k)
                              for k in ("events", "first_up_s",
                                        "first_up_reason",
                                        "first_up_frac",
                                        "first_peak_s",
                                        "forecast_lead_s")},
                }
            if args.autoscale:
                # the control arm: a STATIC fleet at the autoscaled
                # run's mean active replica count — "equal capacity
                # spend" — same workload, same seed. The comparison the
                # row records: did elasticity put its replicas where
                # the burst was?
                ctrl_replicas = max(1, int(round(
                    report["scale"]["mean_replicas"])))
                static = run_distributed_soak(
                    args.index_dir, shards=args.shards,
                    replicas=ctrl_replicas,
                    threads=args.threads, queries=args.queries,
                    seed=args.seed,
                    layout=layout, chaos=args.chaos,
                    worker_deadline_s=(1.0 if args.deadline is None
                                       else args.deadline),
                    timeout_s=args.timeout,
                    flight_dir=args.flight_dir,
                    workload=spec, cache_entries=args.cache,
                    **pacing_kw)
                report["static_control"] = {
                    "replicas": ctrl_replicas,
                    "burst_p99_ms": static["burst_p99_ms"],
                    "served": static["served"],
                    "shed": static["shed"],
                    "errors": static["errors"],
                }
            req_lat = report["latency"].get("router.request") or {}
            p99 = req_lat.get("p99_ms")
            row = {
                # chaos runs, each workload shape, and cache-on vs
                # cache-off are structurally different regimes — each
                # gets its own comparability group, so none drags
                # another's medians (a cached run's 2x QPS must not
                # read as an uncached regression, or vice versa)
                "config": (f"serve_routed-{report['submitted']}q-"
                           f"s{args.shards}r{args.replicas}"
                           + ("-chaos" if args.chaos else "")
                           + ("" if label == "uniform" else f"-{label}")
                           + ("-autoscale" if args.autoscale else "")
                           + (f"-c{cache_n}" if cache_n else "")),
                "backend": jax.default_backend(),
                "shards": args.shards,
                "replicas": args.replicas,
                "workload": label,
                "cache_entries": cache_n,
                "routed_qps": (round(report["served"]
                                     / report["wall_s"], 1)
                               if report["wall_s"] else -1.0),
                "routed_p99_ms": -1.0 if p99 is None else p99,
                "cache_hit_fraction": report["cache"]["hit_fraction"],
                "partial_fraction": report["partial_fraction"],
                "hedge_fired": report["router"].get(
                    "router.hedge_fired", 0),
                "recovery_full": report["recovery_full"],
            }
            if args.autoscale:
                row["scale_events"] = report["scale"]["events"]
                row["burst_p99_ms"] = report["burst_p99_ms"]
                row["overprovision_fraction"] = (
                    report["scale"]["overprovision_fraction"])
                row["mean_replicas"] = report["scale"]["mean_replicas"]
                row["static_replicas"] = (
                    report["static_control"]["replicas"])
                row["static_burst_p99_ms"] = static["burst_p99_ms"]
                row["forecast_burst_p99_ms"] = forecast["burst_p99_ms"]
                row["forecast_lead_s"] = forecast["scale"].get(
                    "forecast_lead_s", -1.0)
                row["reactive_lead_s"] = report["scale"].get(
                    "forecast_lead_s", -1.0)
            report["history"] = append_history_row(row)
            report["history_row"] = row
            reports.append(report)
            ok = ok and (
                report["errors"] == 0 and report["deadlocked"] == 0
                and report["full_mismatches"] == 0
                and report["partial_mismatches"] == 0
                and report["served"] + report["shed"]
                == report["submitted"])
            if static is not None:
                # both arms must conserve, and the elastic arm must not
                # LOSE to equal static spend at the burst peak (a
                # generous bound — bench-check trends the exact number)
                ok = ok and (
                    static["errors"] == 0 and static["deadlocked"] == 0
                    and static["served"] + static["shed"]
                    == static["submitted"])
                if static["burst_p99_ms"] > 0:
                    # a generous smoke bound (a loaded box jitters small
                    # runs by 100s of ms); bench-check trends the exact
                    # burst_p99_ms number across the history
                    ok = ok and (report["burst_p99_ms"]
                                 <= static["burst_p99_ms"] * 1.5 + 250.0)
            if forecast is not None:
                # the predictive arm must conserve too, and its burst
                # p99 must not LOSE to the reactive arm (same generous
                # smoke bound; bench-check trends the exact numbers)
                ok = ok and (
                    forecast["errors"] == 0
                    and forecast["deadlocked"] == 0
                    and forecast["served"] + forecast["shed"]
                    == forecast["submitted"])
                if report["burst_p99_ms"] > 0:
                    ok = ok and (forecast["burst_p99_ms"]
                                 <= report["burst_p99_ms"] * 1.5 + 250.0)
    out = reports[0] if len(reports) == 1 else {
        "runs": reports,
        "levels": [r["history_row"]["workload"] for r in reports]}
    print(json.dumps(out, sort_keys=True, default=repr))
    return 0 if ok else 1


def cmd_serve_bench(args) -> int:
    """Drive the overload soak (serving/soak.py) against an index: N
    worker threads of mixed seeded traffic through a ServingFrontend,
    optionally under a chaos fault plan, reporting the invariant
    counters as JSON. The operational twin of tests/test_serving.py's
    soak — what the tests assert, an operator can reproduce.

    `--shards N [--replicas R]` switches to the ISSUE 10 scatter-gather
    mode: S x R worker processes behind the hedging router, the routed
    chaos soak, and routed_* summary fields in BENCH_HISTORY.jsonl.

    `--concurrency N,N,...` (a comma list) switches to the ISSUE 9
    concurrency SWEEP: closed-loop clients at each level through the
    coalescing frontend, reporting batched p50/p95/p99, QPS, occupancy
    and coalesce-wait histograms, and recompile deltas per level; the
    summary row appends to BENCH_HISTORY.jsonl where `tpu-ir
    bench-check` gates `batched_qps`/`batched_p99_ms`/`solo_p50_ms`/
    `batch_occupancy_mean`."""
    _apply_backend(args)
    if args.autoscale and args.shards is None:
        print("--autoscale needs --shards N: the elastic topology is "
              "the routed worker fleet", file=sys.stderr)
        return 2
    if args.shards is not None:
        return _serve_routed(args)
    from .search import Scorer
    from .serving import DEFAULT_CHAOS_PLAN, ServingConfig, run_soak

    try:
        levels = [int(p) for p in str(args.concurrency).split(",")
                  if p.strip()]
        if any(n < 1 for n in levels):
            raise ValueError
    except ValueError:
        print(f"--concurrency {args.concurrency!r}: expected a positive "
              "integer or a comma list like 1,8,32", file=sys.stderr)
        return 2
    if not levels:
        levels = [4]
    try:
        wl_specs = _workload_specs(args)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    if len(wl_specs) > 1:
        print("a multi-skew sweep records per-skew ROUTED rows; use "
              "--shards N with --skew 0,0.7,1.1 (the single-process "
              "soak takes one skew)", file=sys.stderr)
        return 2
    workload = wl_specs[0][1]
    if len(levels) > 1 and workload is not None:
        # the sweep drives its own fixed closed-loop query set; running
        # it anyway would silently record uniform rows under a zipf flag
        print("--concurrency sweep and --workload zipf are exclusive: "
              "the sweep measures coalescing under a fixed query set; "
              "use the soak (single --concurrency) for traffic shapes",
              file=sys.stderr)
        return 2
    scorer = Scorer.load(args.index_dir, layout=args.layout)
    if len(levels) > 1:
        return _serve_sweep(args, scorer, levels)
    spec = DEFAULT_CHAOS_PLAN if args.chaos else None
    # --faults / TPU_IR_FAULTS install a plan process-wide; run_soak
    # wants to own installation (the serial reference phase must stay
    # clean), so lift the spec off and uninstall. install(None), NOT
    # clear(): clear() forgets the env var was consumed and run_soak's
    # guard would re-read TPU_IR_FAULTS and refuse to run
    from . import faults

    if faults.active() is not None:
        from .utils import envvars

        spec = args.faults or envvars.get_str("TPU_IR_FAULTS") or spec
        faults.install(None)
    with _MaybeTrack(args.metrics_port) as track:
        report = run_soak(
            scorer, threads=args.threads, queries=args.queries,
            seed=args.seed, fault_spec=spec,
            config=ServingConfig(
                max_concurrency=levels[0],
                max_queue=args.queue_depth,
                # soak keeps its historical 0.25 s default; the sweep
                # (above) defaults to no deadline — a padded CPU batch
                # on a large corpus must not degrade mid-measurement
                deadline_s=(0.25 if args.deadline is None
                            else args.deadline),
                breaker_threshold=args.breaker_threshold,
                coalesce=(args.coalesce == "on"),
                cache_entries=args.cache),
            timeout_s=args.timeout, flight_dir=args.flight_dir,
            workload=workload)
        if track.server is not None:
            report["metrics_url"] = track.server.url
    # the soak's query-log view: recorded/slow counts + the last slow
    # captures, so a slow-query incident during the soak is in the JSON
    from .obs import querylog

    report["querylog"] = {**querylog.summary(),
                          "slow_entries": querylog.slow_header_entries()}
    print(json.dumps(report, sort_keys=True, default=repr))
    ok = (report["errors"] == 0 and report["deadlocked"] == 0
          and report["untagged_mismatches"] == 0
          and report["served"] + report["shed"] == report["submitted"])
    return 0 if ok else 1


def cmd_scale(args) -> int:
    """Elastic-serving introspection (ISSUE 16; serving/autoscale.py):
    print the resolved autoscaler configuration — TPU_IR_AUTOSCALE and
    the TPU_IR_SCALE_* knobs as the Autoscaler would actually consume
    them — and, with --url, a live serving process's /healthz
    autoscaler section: membership epoch, per-replica lifecycle state,
    hysteresis counters, and the last scaling decision with its reason.
    The page an operator reads to answer "why did the fleet just grow
    (or refuse to)?" without attaching a debugger."""
    from .serving.autoscale import AutoscaleConfig, autoscale_enabled

    cfg = AutoscaleConfig().resolved()
    out = {
        "enabled": autoscale_enabled(),
        "config": {
            "min_replicas": cfg.min_replicas,
            "max_replicas": cfg.max_replicas,
            "cooldown_s": cfg.cooldown_s,
            "up_occupancy": cfg.up_occupancy,
            "down_occupancy": cfg.down_occupancy,
            "sustain_up": cfg.sustain_up,
            "sustain_down": cfg.sustain_down,
            "drain_timeout_s": cfg.drain_timeout_s,
        },
    }
    if args.url:
        import urllib.request

        url = args.url.rstrip("/") + "/healthz"
        try:
            with urllib.request.urlopen(url, timeout=10.0) as r:
                payload = json.loads(r.read().decode("utf-8"))
        except Exception as e:  # noqa: BLE001 — a dead server is the
            # answer here, not a traceback
            print(f"error: cannot read {url}: {e!r}", file=sys.stderr)
            return 1
        out["live"] = payload.get("autoscaler") or {
            "error": "no autoscaler registered in that process"}
    print(json.dumps(out, sort_keys=True))
    return 0


_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def _sparkline(values, width: int = 48) -> str:
    """Unicode block sparkline over the last `width` values."""
    vs = list(values)[-width:]
    if not vs:
        return ""
    lo, hi = min(vs), max(vs)
    span = hi - lo
    if span <= 0:
        return _SPARK_BLOCKS[0] * len(vs)
    return "".join(
        _SPARK_BLOCKS[min(len(_SPARK_BLOCKS) - 1,
                          int((v - lo) / span * len(_SPARK_BLOCKS)))]
        for v in vs)


def cmd_top(args) -> int:
    """The telemetry time machine's terminal view (ISSUE 19;
    obs/timeseries.py): one line per curated series — newest value,
    min/max over the tier, and a unicode sparkline of the retained
    window. Reads the local process store by default (useful inside a
    soak), or a live server's /timeseries via --url. --watch N
    redraws N times at --interval seconds; --json prints the raw
    /timeseries payload instead (the scriptable form)."""

    def _fetch() -> dict:
        if args.url:
            import urllib.request

            url = args.url.rstrip("/") + "/timeseries"
            with urllib.request.urlopen(url, timeout=10.0) as r:
                return json.loads(r.read().decode("utf-8"))
        from .obs import timeseries

        return timeseries.payload()

    try:
        payload = _fetch()
    except Exception as e:  # noqa: BLE001 — a dead server is the answer
        print(f"error: cannot read /timeseries: {e!r}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(payload, sort_keys=True))
        return 0
    tier = max(0, args.tier)
    for it in range(max(1, args.watch)):
        if it:
            time.sleep(args.interval)
            try:
                payload = _fetch()
            except Exception as e:  # noqa: BLE001
                print(f"error: cannot read /timeseries: {e!r}",
                      file=sys.stderr)
                return 1
            print("\x1b[2J\x1b[H", end="")  # clear + home between draws
        if not payload.get("enabled"):
            print("timeseries disabled (TPU_IR_TIMESERIES=0)")
            return 0
        tiers = payload.get("tiers", [])
        if tier >= len(tiers):
            print(f"error: tier {tier} out of range "
                  f"(store has {len(tiers)})", file=sys.stderr)
            return 2
        t = tiers[tier]
        print(f"tpu-ir top — tier {tier} "
              f"({t['window_s']:g}s x {t['capacity']} windows, "
              f"{t['len']} held)")
        for label in sorted(payload.get("series", {})):
            pts = payload["series"][label]["tiers"][tier]
            vals = [v for _, v in pts]
            if not vals:
                print(f"  {label:<24} (no data)")
                continue
            print(f"  {label:<24} {vals[-1]:>10.3f}  "
                  f"[{min(vals):.3f}..{max(vals):.3f}]  "
                  f"{_sparkline(vals)}")
        anomalies = payload.get("anomalies") or []
        if anomalies:
            a = anomalies[-1]
            print(f"  last anomaly: {a['series']} z={a['z']} "
                  f"value={a['value']} median={a['median']}")
        fit = payload.get("forecast")
        if fit:
            print(f"  forecast: period={fit['period_s']:g}s "
                  f"amplitude={fit['amplitude']:g} r2={fit['r2']:g} "
                  f"-> occupancy {fit.get('forecast', 0.0):g} "
                  f"in {fit.get('lead_s', 0.0):g}s")
    return 0


def cmd_cache(args) -> int:
    """The result-cache tier's CLI (ISSUE 15; serving/result_cache.py):
    `stats` prints the process-wide cache.* counters + every live
    cache's control-plane snapshot; `clear` drops all live caches'
    entries and resets the cache.* counters. Per-process like
    `tpu-ir stats` — meaningful from a serving or bench process."""
    from .obs import get_registry
    from .serving.result_cache import cache_counters, clear_all, live_caches

    out = {
        "counters": cache_counters(),
        "caches": [c.snapshot() for c in live_caches()],
    }
    if args.verb == "clear":
        out["cleared_entries"] = clear_all()
        get_registry().reset_counters("cache.")
        out["counters_reset"] = True
    print(json.dumps(out, sort_keys=True))
    return 0


def cmd_compact(args) -> int:
    """Explicit merge/compaction driver for a live index (ISSUE 15
    satellite — the other half of TPU_IR_MERGE_AUTO=0): by default
    drains the tiered merge policy's debt (repeated plan_merges steps —
    exactly what auto-merge would have run inline after flushes); with
    --all folds EVERYTHING into one canonical servable segment.
    Serving never waits on this: readers keep their committed
    generation until the final atomic rename publishes the next."""
    _apply_backend(args)
    from .index import segments as seg
    from .index.ingest import IngestWriter

    if not seg.is_live(args.live_dir):
        print(f"error: {args.live_dir} is not a live index dir "
              "(`tpu-ir ingest DIR --init` creates one)", file=sys.stderr)
        return 1
    writer = IngestWriter(args.live_dir, auto_merge=False)
    before = writer.live.manifest()
    if args.all:
        m = writer.compact_all()
        steps = 1
    else:
        drained = writer.drain_merges(max_steps=args.max_steps)
        m, steps = drained["manifest"], drained["steps"]
    out = {
        "live_dir": os.path.abspath(args.live_dir),
        "mode": "all" if args.all else "drain",
        "steps": steps,
        "segments_before": len(before["segments"]),
        "segments": m["segments"],
        "generation": writer.live.current_gen(),
        **writer.live.doc_counts(),
    }
    if args.gc:
        out["gc"] = writer.live.gc()
    print(json.dumps(out, sort_keys=True))
    return 0


def cmd_serve_worker(args) -> int:
    """Standalone shard worker (ISSUE 15 satellite; ROADMAP 5 cross-host
    ergonomics): load a doc-range-restricted scorer and serve the /rpc
    surface the router fans out to — the same serve_worker() the
    ShardSet subprocesses run, minus the parent-death plumbing, so a
    static address grid can span hosts (`Router(dir, [[\"hostA:9201\"],
    [\"hostB:9201\"]])`; RUNBOOK §21 has the recipe). Prints one ready
    JSON line (addr/shard/pid) on stdout, then serves until SIGTERM /
    Ctrl-C; --run-for S bounds the lifetime (smoke tests, drills)."""
    _apply_backend(args)
    import threading as _threading

    from .serving.shardset import serve_worker

    try:
        shard_s, _, total_s = args.shard.partition("/")
        shard, num_shards = int(shard_s), int(total_s)
    except ValueError:
        print(f"--shard {args.shard!r}: expected i/S (e.g. 0/4)",
              file=sys.stderr)
        return 2
    if not (0 <= shard < num_shards):
        print(f"--shard {args.shard!r}: shard index out of range",
              file=sys.stderr)
        return 2
    layout = "sparse" if args.layout == "auto" else args.layout
    server, frontend, scorer = serve_worker(
        args.index_dir, shard, num_shards, layout=layout,
        port=args.port, host=args.host, replica=args.replica,
        deadline_s=args.deadline,
        max_concurrency=args.concurrency, max_queue=args.queue_depth,
        warm=not args.no_warm)
    print(json.dumps({
        "addr": f"{args.host}:{server.port}", "port": server.port,
        "shard": shard, "num_shards": num_shards,
        "replica": args.replica, "pid": os.getpid(),
        "index_generation": scorer.generation,
        "doc_range": list(scorer.doc_range or ()),
    }, sort_keys=True), flush=True)
    stop = _threading.Event()
    try:
        import signal as _signal

        _signal.signal(_signal.SIGTERM, lambda *_: stop.set())
    except (ValueError, OSError):  # non-main thread (tests)
        pass
    try:
        stop.wait(args.run_for if args.run_for else None)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
        from . import faults

        faults.drain_abandoned(timeout_s=5.0)
    return 0


def cmd_eval(args) -> int:
    """Score a trec_eval-format run against qrels (search/evaluate.py):
    MAP / MRR / NDCG@10 / P@5 / P@10 / recall@100, no external tooling."""
    from .search.evaluate import evaluate_run, read_qrels, read_run

    out = evaluate_run(read_run(args.run), read_qrels(args.qrels),
                       complete=args.complete)
    print(json.dumps(out))
    return 0 if out.get("queries") else 1


def cmd_merge(args) -> int:
    """Merge built indexes into one (incremental corpus growth: index new
    batches separately, merge). Byte-identical to a single build over the
    concatenated corpus (index/merge.py)."""
    _apply_backend(args)
    from .index.merge import merge_indexes

    meta = merge_indexes(args.sources, args.out_dir,
                         num_shards=args.shards,
                         compute_chargrams=not args.no_chargrams,
                         overwrite=args.overwrite)
    print(json.dumps(meta.__dict__))
    return 0


def cmd_pack(args) -> int:
    """PackTextFile equivalent: each line of a plain text file becomes one
    TREC <DOC> with docid PREFIX-NNNNNNN (reference
    edu/umd/cloud9/io/PackTextFile.java packs lines into SequenceFiles).

    --format trectext/trecweb instead re-parses the input with the
    matching stream parser (collection/parsers.py — live versions of the
    reference's dead TrecTextParser/TrecWebParser) and canonicalizes each
    parsed document into the indexers' native TREC shape."""
    from .collection.parsers import Document, TrecTextParser, TrecWebParser, to_trec

    with open(args.text_file, encoding="utf-8") as fin, \
            open(args.output, "w", encoding="utf-8") as fout:
        if args.format == "lines":
            docs = (Document(f"{args.prefix}-{i:07d}", line.rstrip("\n"))
                    for i, line in enumerate(fin))
        else:
            cls = TrecTextParser if args.format == "trectext" \
                else TrecWebParser
            docs = iter(cls(fin))
        n = 0
        for doc in docs:
            fout.write(to_trec(doc))
            n += 1
    print(json.dumps({"docs_packed": n, "output": args.output,
                      "format": args.format}))
    return 0


def cmd_count(args) -> int:
    """DemoCountTrecDocuments equivalent: stream a corpus, count docs, report
    docid range (reference sa/edu/kaust/indexing/DemoCountTrecDocuments.java
    maps (docid, docno) and keeps the max)."""
    from .collection import read_trec_corpus

    n = 0
    first = last = None
    for doc in read_trec_corpus(args.corpus):
        d = doc.docid
        first = d if first is None or d < first else first
        last = d if last is None or d > last else last
        n += 1
    print(json.dumps({"Count.DOCS": n, "min_docid": first,
                      "max_docid": last}))
    return 0


def cmd_docno(args) -> int:
    """Docno-mapping inspection (reference TrecDocnoMapping.main:
    `list | getDocno docid | getDocid docno`,
    edu/umd/cloud9/collection/trec/TrecDocnoMapping.java:164-200)."""
    from .collection import DocnoMapping
    from .index import format as fmt

    mapping = DocnoMapping.load(os.path.join(args.index_dir, fmt.DOCNOS))
    if args.op != "list" and args.arg is None:
        print(f"usage: tpu-ir docno INDEX_DIR {args.op} "
              f"{'DOCID' if args.op == 'getDocno' else 'DOCNO'}",
              file=sys.stderr)
        return 1
    if args.op == "list":
        # reference column order: docno first
        # (TrecDocnoMapping.java list branch prints i + "\t" + mDocids[i])
        for docno in range(1, len(mapping) + 1):
            print(f"{docno}\t{mapping.get_docid(docno)}")
    elif args.op == "getDocno":
        try:
            print(mapping.get_docno(args.arg))
        except KeyError:
            print(f"docid {args.arg!r} not found", file=sys.stderr)
            return 1
    else:  # getDocid
        try:
            docno = int(args.arg)
        except ValueError:
            print(f"invalid docno {args.arg!r}", file=sys.stderr)
            return 1
        if not 1 <= docno <= len(mapping):
            print(f"docno {docno} out of range 1..{len(mapping)}",
                  file=sys.stderr)
            return 1
        print(mapping.get_docid(docno))
    return 0


def cmd_expand(args) -> int:
    from .search import WildcardLookup

    lookup = WildcardLookup.load(args.index_dir, args.chargram_k)
    m = re.fullmatch(r"(.+?)~(\d?)", args.pattern)
    if m:  # fuzzy: 'term~' (1 edit), 'term~0' (exact), 'term~2'
        from .search.wildcard import MAX_FUZZY_EDITS

        d = min(int(m.group(2)) if m.group(2) else 1, MAX_FUZZY_EDITS)
        for term, dist in lookup.fuzzy(m.group(1), max_edits=d,
                                       limit=args.n):
            print(f"{term}\t{dist}")
        return 0
    for term in lookup.expand(args.pattern, limit=args.n):
        print(term)
    return 0


# commands whose whole job is LOADING artifacts the user named: only for
# these does a FileNotFoundError mean "you pointed me at the wrong thing"
# (clean message); everywhere else it keeps its traceback
_ARTIFACT_ENTRY_CMDS = frozenset({
    "cmd_search", "cmd_inspect", "cmd_verify", "cmd_warm", "cmd_docno",
    "cmd_expand", "cmd_eval", "cmd_count", "cmd_pack", "cmd_merge",
    "cmd_serve_bench", "cmd_migrate_index", "cmd_doctor",
    "cmd_generations", "cmd_backup",
})


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="tpu-ir")
    sub = p.add_subparsers(dest="cmd", required=True)

    pi = sub.add_parser("index", help="build all index artifacts for a corpus")
    pi.add_argument("corpus", nargs="+", help="TREC files or directories")
    pi.add_argument("index_dir")
    pi.add_argument("--k", type=int, default=1, help="term-k-gram size")
    pi.add_argument("--chargram-k", type=int, nargs="*", default=[2, 3])
    pi.add_argument("--shards", type=int, default=10,
                    help="term shards (reference used 10 reducers)")
    pi.add_argument("--overwrite", action="store_true")
    pi.add_argument("--no-chargrams", action="store_true")
    pi.add_argument("--streaming", action="store_true",
                    help="out-of-core spill/merge build for corpora larger "
                         "than memory")
    pi.add_argument("--batch-docs", type=int, default=50000,
                    help="streaming: documents per tokenize batch")
    pi.add_argument("--radix-buckets", type=int, default=None,
                    metavar="B",
                    help="streaming: radix-partition pass-1 pair spills "
                         "into B buckets so pass 2 runs as per-bucket "
                         "local device reduces (default: "
                         "$TPU_IR_RADIX_BUCKETS, 0 = per-batch combine; "
                         "artifacts are bit-identical either way)")
    pi.add_argument("--tokenize-procs", type=int, default=None,
                    metavar="N",
                    help="worker processes for the pure-Python tokenizer "
                         "path (default: $TPU_IR_TOKENIZE_PROCS; spills "
                         "are byte-identical to the serial tokenizer)")
    pi.add_argument("--spmd-devices", type=int, default=None,
                    help="build over an N-device mesh (doc-sharded map, "
                         "all_to_all shuffle, term-sharded reduce); implies "
                         "N index shards; composes with --streaming for "
                         "out-of-core corpora")
    pi.add_argument("--positions", action="store_true",
                    help="format v2: also write per-posting position runs "
                         "(enables \"quoted phrase\" and --prox queries)")
    pi.add_argument("--store", action="store_true",
                    help="also build the compressed document-text store "
                         "(one extra corpus pass; enables search "
                         "--snippets)")
    pi.add_argument("--track", type=int, default=None, metavar="PORT",
                    help="serve live build progress over HTTP for the "
                         "duration of the build (/jobs /metrics /healthz; "
                         "0 = ephemeral port, announced on stderr)")
    _add_backend_arg(pi)
    pi.set_defaults(fn=cmd_index)

    ps = sub.add_parser(
        "search",
        help="query an index (REPL or batch); glob tokens like te* expand "
             "over the char-k-gram index (OR of up to 64 matching terms)")
    ps.add_argument("index_dir")
    ps.add_argument("--query", "-q")
    ps.add_argument("--queries-file")
    ps.add_argument("--topics", metavar="FILE", default=None,
                    help="TREC topics file (<top>/<num>/<title> records); "
                         "titles become the queries, topic numbers the "
                         "qids for --trec-run")
    ps.add_argument("--k", type=int, default=10, help="results per query")
    ps.add_argument("--scoring", choices=["tfidf", "bm25"], default="tfidf")
    ps.add_argument("--rerank", type=int, default=None, metavar="N",
                    help="two-stage retrieval: BM25 top-N candidates, then "
                         "cosine TF-IDF rerank")
    ps.add_argument("--prox", action="store_true",
                    help="add the positions-based proximity boost to the "
                         "rerank (needs an index built with --positions)")
    ps.add_argument("--slop", type=int, default=0, metavar="S",
                    help="\"quoted phrase\" matching tolerates S extra "
                         "token gaps (0 = exact adjacency)")
    ps.add_argument("--show-matches", action="store_true",
                    help="print each hit's query-term token positions "
                         "(needs an index built with --positions)")
    ps.add_argument("--snippets", action="store_true",
                    help="print a query-highlighted text window per hit "
                         "(needs an index built with --store)")
    ps.add_argument("--layout",
                    choices=["auto", "dense", "sparse", "sharded"],
                    default="auto",
                    help="'sharded' distributes the tiered layout's doc "
                         "axis over all devices (TF-IDF/BM25/rerank) with "
                         "a global top-k merge")
    ps.add_argument("--docnos", action="store_true",
                    help="print docnos instead of docids")
    ps.add_argument("--compat", action="store_true",
                    help="reproduce reference quirks (int-division idf, "
                         "1-2 word query cap)")
    ps.add_argument("--trec-run", metavar="TAG", default=None,
                    help="emit standard trec_eval run lines "
                         "('qid Q0 docid rank score TAG'; qids are "
                         "1-based query positions) instead of the "
                         "human-readable listing")
    _add_backend_arg(ps)
    ps.set_defaults(fn=cmd_search)

    pn = sub.add_parser(
        "inspect",
        help="dump index records, or ANY framework artifact — part/"
             "positions shards, build spills, pass-1 manifests, serving "
             "caches, npy/tsv side files (ReadSequenceFile generality)")
    pn.add_argument("index_dir", metavar="path",
                    help="index dir, artifact file, or artifact dir")
    pn.add_argument("-n", type=int, default=20,
                    help="max terms / records to print")
    pn.add_argument("--postings", type=int, default=10,
                    help="max postings per term")
    pn.add_argument("--term", default=None,
                    help="print one term's postings via the dictionary "
                         "(the reference getValue seek); input is analyzed "
                         "like a query")
    _add_backend_arg(pn)
    pn.set_defaults(fn=cmd_inspect)

    pv = sub.add_parser("verify", help="validate index structural invariants")
    pv.add_argument("index_dir")
    pv.set_defaults(fn=cmd_verify)

    pmi = sub.add_parser(
        "migrate-index",
        help="convert part shards between artifact formats in place "
             "(npz v1 <-> arena v2 <-> compressed v3; atomic per shard, "
             "checksums re-recorded, idempotent)")
    pmi.add_argument("index_dir")
    pmi.add_argument("--to", type=int, choices=[1, 2, 3], default=2,
                     help="target format_version (3 = compressed arenas, "
                          "2 = zero-copy arenas, 1 = npz rollback)")
    pmi.add_argument("--compress", action="store_true",
                     help="shorthand for --to 3: bit-pack doc columns on "
                          "the block-max grid and quantize tf "
                          "(RUNBOOK §26)")
    pmi.add_argument("--decompress", action="store_true",
                     help="shorthand for --to 2: walk a compressed index "
                          "back to raw arenas (byte-identical when the "
                          "tf mode was lossless)")
    pmi.add_argument("--tf-dtype", choices=["auto", "int8", "bf16"],
                     default=None,
                     help="tf quantization for --compress (default: "
                          "TPU_IR_TF_DTYPE; auto = int8 when lossless "
                          "everywhere, else bf16)")
    pmi.add_argument("--add-bounds", action="store_true",
                     help="backfill the block-max bounds artifact "
                          "(blockmax.arena) from the postings in place — "
                          "no part rewrite, idempotent, verify-clean "
                          "(RUNBOOK §20)")
    pmi.set_defaults(fn=cmd_migrate_index)

    pin = sub.add_parser(
        "ingest",
        help="live index writes: buffered add/update/delete flushed to "
             "delta segments + tombstones, tiered merges, compaction "
             "(RUNBOOK §19)")
    pin.add_argument("live_dir", help="live index dir (see --init)")
    pin.add_argument("--init", action="store_true",
                     help="create the live dir first if it is not one "
                          "yet (pins k/shards/chargrams for every "
                          "future segment)")
    pin.add_argument("--add", nargs="*", default=[], metavar="TREC",
                     help="TREC corpus file(s) to ADD (a docid that "
                          "already exists is an error — use --update)")
    pin.add_argument("--update", nargs="*", default=[], metavar="TREC",
                     help="TREC corpus file(s) to UPSERT (existing "
                          "copies are tombstoned)")
    pin.add_argument("--delete", nargs="*", default=[], metavar="DOCID",
                     help="docids to tombstone (unknown ids are "
                          "ignored — idempotent feed semantics)")
    pin.add_argument("--merge", action="store_true",
                     help="run one tiered-merge step if any size tier "
                          "carries merge debt")
    pin.add_argument("--compact", action="store_true",
                     help="full compaction: one canonical segment, "
                          "zero tombstones — the generation serving "
                          "swaps to (bit-identical to a from-scratch "
                          "build of the surviving docs)")
    pin.add_argument("--gc", action="store_true",
                     help="prune generations past "
                          "TPU_IR_INGEST_KEEP_GENERATIONS and delete "
                          "unreferenced segment dirs")
    pin.add_argument("--buffer-docs", type=int, default=None,
                     help="auto-flush threshold (default: "
                          "TPU_IR_INGEST_BUFFER_DOCS)")
    pin.add_argument("--no-auto-merge", action="store_true",
                     help="skip the post-flush tiered-merge check")
    pin.add_argument("--k", type=int, default=1,
                     help="--init: term-k-gram size (live indexes "
                          "support k=1 only)")
    pin.add_argument("--shards", type=int, default=10,
                     help="--init: term shards per segment")
    pin.add_argument("--chargram-k", type=int, nargs="*",
                     default=[2, 3], help="--init: char-gram sizes")
    pin.add_argument("--swap-bench", action="store_true",
                     help="run the ingest->compact->swap micro-bench "
                          "against live_dir (created if missing) and "
                          "append swap_gap_ms to BENCH_HISTORY.jsonl")
    pin.add_argument("--soak-bench", action="store_true",
                     help="run the durable ingest+serve soak (child "
                          "feeder SIGKILLed mid-stream + exactly-once "
                          "recovery, probes serving throughout) and "
                          "append ingest_docs_per_s / freshness_lag_ms "
                          "to BENCH_HISTORY.jsonl")
    _add_backend_arg(pin)
    pin.set_defaults(fn=cmd_ingest)

    pbk = sub.add_parser(
        "backup",
        help="generation-pinned hardlink snapshot of a live dir "
             "(current manifest + referenced segments + WAL tail; "
             "acked-but-unflushed writes ride the WAL) — or, with "
             "--restore, materialize+verify a snapshot into a new dir")
    pbk.add_argument("src", help="live dir to snapshot (or, with "
                                 "--restore, the backup to restore)")
    pbk.add_argument("dest", help="destination dir (must not exist or "
                                  "be empty)")
    pbk.add_argument("--restore", action="store_true",
                     help="treat src as a backup: link/copy it into "
                          "dest and run the full verify_live gauntlet "
                          "on the result")
    pbk.set_defaults(fn=cmd_backup)

    pgen = sub.add_parser(
        "generations",
        help="list a live index's generation chain: segments, doc "
             "counts, tombstones, servability")
    pgen.add_argument("live_dir")
    pgen.add_argument("-n", type=int, default=None,
                      help="newest N generations only")
    pgen.add_argument("--gc", action="store_true",
                      help="prune old generations + unreferenced "
                           "segments after listing")
    pgen.set_defaults(fn=cmd_generations)

    pw = sub.add_parser("warm", help="prebuild the serving cache (tiered "
                                     "layout + df + rerank norms) so later "
                                     "process starts take the fast path")
    pw.add_argument("index_dir")
    pw.add_argument("--layout", choices=["auto", "dense", "sparse", "sharded"],
                    default="sparse")
    _add_backend_arg(pw)
    pw.set_defaults(fn=cmd_warm)

    pm = sub.add_parser("merge", help="merge built indexes into one "
                                      "(same artifacts as one build over "
                                      "the concatenated corpus)")
    pm.add_argument("sources", nargs="+", help="source index dirs")
    pm.add_argument("out_dir", help="output index dir")
    pm.add_argument("--shards", type=int, default=10)
    pm.add_argument("--no-chargrams", action="store_true")
    pm.add_argument("--overwrite", action="store_true",
                    help="delete an existing output index first")
    _add_backend_arg(pm)
    pm.set_defaults(fn=cmd_merge)

    pst = sub.add_parser(
        "stats", help="dump the process-wide recovery + serving counters, "
                      "fault-plan fire counts and latency histograms as "
                      "JSON")
    pst.add_argument("--reset", action="store_true",
                     help="zero the telemetry registry after reading "
                          "(per-interval scrapes instead of lifetime "
                          "counts)")
    pst.add_argument("--cluster", action="store_true",
                     help="merge the spooled per-process snapshots "
                          "(TPU_IR_TELEMETRY_DIR) into cluster totals "
                          "instead of reading this process's registry")
    pst.add_argument("--telemetry-dir", default=None, metavar="DIR",
                     help="spool directory for --cluster (default: "
                          "TPU_IR_TELEMETRY_DIR)")
    pst.set_defaults(fn=cmd_stats)

    pmx = sub.add_parser(
        "metrics", help="dump the unified TelemetryRegistry (counters + "
                        "latency histograms) as JSON, or Prometheus text "
                        "with --prom")
    pmx.add_argument("--prom", action="store_true",
                     help="Prometheus text exposition format")
    pmx.add_argument("--reset", action="store_true",
                     help="zero the telemetry registry after reading")
    pmx.add_argument("--cluster", action="store_true",
                     help="merge the spooled per-process snapshots "
                          "(TPU_IR_TELEMETRY_DIR) into cluster totals "
                          "instead of reading this process's registry")
    pmx.add_argument("--telemetry-dir", default=None, metavar="DIR",
                     help="spool directory for --cluster (default: "
                          "TPU_IR_TELEMETRY_DIR)")
    pmx.set_defaults(fn=cmd_metrics)

    ptd = sub.add_parser(
        "trace-dump", help="dump the flight-recorder ring (recent span "
                           "trees) + a telemetry snapshot as JSONL")
    ptd.add_argument("--out", default=None,
                     help="write the JSONL here instead of stdout")
    ptd.set_defaults(fn=cmd_trace_dump)

    ppr = sub.add_parser(
        "profile", help="device-cost profiling report: per-signature "
                        "compile counts + FLOPs/bytes, dispatch time "
                        "split, memory gauges, recompile window")
    ppr.set_defaults(fn=cmd_profile)

    pql = sub.add_parser(
        "querylog", help="dump the sampled query log: per-request "
                         "entries (terms/hash, level, stage split, "
                         "top-k, prune decision) + slow-query captures")
    pql.add_argument("-n", type=int, default=None,
                     help="newest N entries only (default: the whole "
                          "ring)")
    pql.add_argument("--slow", action="store_true",
                     help="slow-query captures only (span tree + "
                          "explain of trapped offenders)")
    pql.add_argument("--trace", default=None, metavar="TRACE_ID",
                     help="only entries recorded under this distributed "
                          "trace id (the `tpu-ir trace` join key)")
    pql.set_defaults(fn=cmd_querylog)

    ptr = sub.add_parser(
        "trace", help="distributed request traces: list known trace ids "
                      "(store + TPU_IR_TELEMETRY_DIR span spool), or "
                      "stitch one id's cross-process waterfall")
    ptr.add_argument("trace_id", nargs="?", default=None,
                     help="trace id to stitch (omit to list)")
    ptr.add_argument("--json", action="store_true",
                     help="print the stitched span tree as JSON instead "
                          "of the ASCII waterfall")
    ptr.set_defaults(fn=cmd_trace)

    pdr = sub.add_parser(
        "doctor", help="index health report: df skew, per-shard "
                       "term/doc balance, tier occupancy, arena "
                       "section sizes, heuristic warnings")
    pdr.add_argument("index_dir")
    pdr.add_argument("--top", type=int, default=10,
                     help="top-N terms by df to list")
    pdr.set_defaults(fn=cmd_doctor)

    pbc = sub.add_parser(
        "bench-check",
        help="BENCH_HISTORY.jsonl regression sentry: newest row vs the "
             "trailing-window median per metric; non-zero exit on breach")
    pbc.add_argument("--history", default=None, metavar="PATH",
                     help="history file (default: BENCH_HISTORY.jsonl in "
                          "the CWD, then the repo checkout)")
    pbc.add_argument("--window", type=int, default=None,
                     help="trailing comparable rows to median over "
                          "(default TPU_IR_BENCH_CHECK_WINDOW)")
    pbc.add_argument("--min-rows", type=int, default=None,
                     help="comparable prior rows required to enforce "
                          "(default TPU_IR_BENCH_CHECK_MIN_ROWS)")
    pbc.add_argument("--tolerance", type=float, default=None,
                     help="relative degradation vs the median that "
                          "breaches (default TPU_IR_BENCH_CHECK_TOLERANCE)")
    pbc.add_argument("--self-test", action="store_true",
                     help="gate mode: insufficient history is a clean "
                          "skip (exit 0) instead of exit 2")
    pbc.set_defaults(fn=cmd_bench_check)

    pb = sub.add_parser(
        "serve-bench",
        help="overload soak: mixed multi-threaded traffic through the "
             "serving frontend (admission control + degradation ladder + "
             "circuit breaker), optionally under an injected chaos plan")
    pb.add_argument("index_dir")
    pb.add_argument("--threads", type=int, default=8,
                    help="concurrent client worker threads")
    pb.add_argument("--queries", type=int, default=240,
                    help="total mixed queries across all workers")
    pb.add_argument("--seed", type=int, default=0,
                    help="workload + chaos seed (runs are replayable)")
    pb.add_argument("--concurrency", default="4",
                    help="admission: requests executing at once; a comma "
                         "list (e.g. 1,8,32) runs the coalescing "
                         "concurrency SWEEP instead of the soak, one "
                         "closed-loop pass per level (--queries becomes "
                         "queries per level)")
    pb.add_argument("--queue-depth", type=int, default=8,
                    help="admission: max requests waiting for a slot "
                         "(past this, requests shed immediately)")
    pb.add_argument("--deadline", type=float, default=None,
                    help="per-request device dispatch deadline (s); "
                         "default 0.25 for the soak, none for the sweep")
    pb.add_argument("--coalesce", choices=["auto", "on", "off"],
                    default="auto",
                    help="continuous micro-batching (serving/batching.py):"
                         " auto = off for the soak, on for the sweep")
    pb.add_argument("--breaker-threshold", type=int, default=4,
                    help="consecutive device failures that open the "
                         "circuit breaker")
    pb.add_argument("--timeout", type=float, default=300.0,
                    help="whole-soak wall-clock bound (s); requests still "
                         "pending past it count as deadlocked")
    pb.add_argument("--chaos", action="store_true",
                    help="inject the default chaos plan (hangs + device "
                         "losses on the score dispatch); --faults SPEC "
                         "overrides with a custom plan. In --shards mode "
                         "chaos is process kills: a replica SIGKILL, then "
                         "a whole shard, then respawn")
    pb.add_argument("--shards", type=int, default=None, metavar="N",
                    help="scatter-gather mode (serving/router.py): spawn "
                         "N doc-shard worker processes behind a hedging "
                         "query router and drive the routed soak instead "
                         "of the single-process one; summary fields "
                         "routed_qps/routed_p99_ms/partial_fraction/"
                         "hedge_fired append to BENCH_HISTORY.jsonl")
    pb.add_argument("--replicas", type=int, default=1, metavar="R",
                    help="replicas per shard in --shards mode (failover "
                         "+ hedging need R >= 2)")
    pb.add_argument("--autoscale", action="store_true",
                    help="elastic --shards mode (serving/autoscale.py): "
                         "run the routed soak under the closed-loop "
                         "autoscaler (warm grow on sustained pressure, "
                         "drain-not-drop retire on idleness), then a "
                         "static control at the same mean replica "
                         "count; scale_events / burst_p99_ms / "
                         "overprovision_fraction append to "
                         "BENCH_HISTORY.jsonl. Also runs the "
                         "forecast-vs-reactive A/B arm (obs/"
                         "timeseries.py): the diurnal-fit third "
                         "scale-up signal vs plain occupancy, "
                         "forecast_lead_s / forecast_burst_p99_ms "
                         "recorded next to the reactive numbers")
    pb.add_argument("--lead", type=float, default=1.0, metavar="S",
                    help="forecast horizon (seconds) for the "
                         "--autoscale predictive arm: the diurnal fit "
                         "publishes occupancy predicted this far "
                         "ahead, so scale-up leads the burst by about "
                         "this much (live serving uses "
                         "TPU_IR_SCALE_LEAD_S instead)")
    pb.add_argument("--layout",
                    choices=["auto", "dense", "sparse", "sharded"],
                    default="auto")
    pb.add_argument("--workload", choices=["uniform", "zipf"],
                    default=None,
                    help="traffic shape (serving/workload.py): uniform "
                         "= the legacy seeded mixed draw; zipf = "
                         "rank-skewed term draw over the df-ordered "
                         "vocabulary. Default: TPU_IR_WORKLOAD")
    pb.add_argument("--skew", default=None, metavar="S[,S...]",
                    help="Zipf exponent(s) for --workload zipf; a comma "
                         "list in --shards mode runs one routed soak "
                         "PER level and appends one BENCH_HISTORY row "
                         "each (0 = uniform control). Default: "
                         "TPU_IR_WORKLOAD_SKEW")
    pb.add_argument("--burst", type=float, default=None,
                    help="diurnal burst amplitude for the workload "
                         "arrival schedule (default: "
                         "TPU_IR_WORKLOAD_BURST)")
    pb.add_argument("--pacing", type=float, default=None, metavar="S",
                    help="mean inter-arrival pacing unit (seconds) for "
                         "the routed soak's open-ish arrival schedule; "
                         "raise it so arrivals (not service time) set "
                         "the occupancy wave — the regime the "
                         "--autoscale A/B needs (default: the soak's "
                         "0.002)")
    pb.add_argument("--cache", type=int, default=None, metavar="N",
                    help="generation-keyed exact-hit result cache "
                         "capacity (entries) at the router / frontend "
                         "(serving/result_cache.py); 0 disables. "
                         "Default: TPU_IR_CACHE_RESULTS")
    pb.add_argument("--flight-dir", default=None,
                    help="where an invariant breach writes its "
                         "flight-recorder JSONL (default: "
                         "TPU_IR_FLIGHT_DIR or the system temp dir)")
    pb.add_argument("--metrics-port", type=int, default=None,
                    metavar="PORT",
                    help="serve live telemetry over HTTP for the "
                         "duration of the soak (/metrics /healthz /jobs "
                         "/flight; 0 = ephemeral port, announced on "
                         "stderr)")
    _add_backend_arg(pb)
    pb.set_defaults(fn=cmd_serve_bench)

    psc = sub.add_parser(
        "scale",
        help="elastic-serving introspection (serving/autoscale.py): "
             "the resolved TPU_IR_AUTOSCALE / TPU_IR_SCALE_* config, "
             "plus a live server's /healthz autoscaler section "
             "(epoch, per-replica lifecycle, last decision) via --url")
    psc.add_argument("--url", default=None, metavar="URL",
                     help="base URL of a running --metrics-port "
                          "telemetry server; prints its /healthz "
                          "autoscaler section")
    psc.set_defaults(fn=cmd_scale)

    ptp = sub.add_parser(
        "top",
        help="live terminal view of the telemetry time machine "
             "(obs/timeseries.py): one sparkline per curated series "
             "(rates, occupancy, per-window percentiles) off the "
             "local store or a live server's /timeseries via --url")
    ptp.add_argument("--url", default=None, metavar="URL",
                     help="base URL of a running --metrics-port "
                          "telemetry server; default reads this "
                          "process's own store")
    ptp.add_argument("--tier", type=int, default=0,
                     help="ring tier to render (0 = finest)")
    ptp.add_argument("--watch", type=int, default=1, metavar="N",
                     help="redraw N times before exiting (1 = one "
                          "shot)")
    ptp.add_argument("--interval", type=float, default=2.0,
                     help="seconds between --watch redraws")
    ptp.add_argument("--json", action="store_true",
                     help="print the raw /timeseries payload instead "
                          "of the terminal view")
    ptp.set_defaults(fn=cmd_top)

    pca = sub.add_parser(
        "cache",
        help="result-cache tier introspection: cache.* counters + live "
             "cache snapshots (stats), or drop every live cache's "
             "entries and reset the counters (clear)")
    pca.add_argument("verb", nargs="?", choices=["stats", "clear"],
                     default="stats")
    pca.set_defaults(fn=cmd_cache)

    pco = sub.add_parser(
        "compact",
        help="drive live-index merges explicitly (the TPU_IR_MERGE_AUTO"
             "=0 companion): drain the tiered merge policy's debt, or "
             "--all for full compaction into one canonical segment")
    pco.add_argument("live_dir")
    pco.add_argument("--all", action="store_true",
                     help="full compaction (every segment + tombstone "
                          "folded into one canonical servable segment)")
    pco.add_argument("--max-steps", type=int, default=64,
                     help="bound on tiered merge steps when draining")
    pco.add_argument("--gc", action="store_true",
                     help="prune old generation manifests + "
                          "unreferenced segment dirs afterwards")
    _add_backend_arg(pco)
    pco.set_defaults(fn=cmd_compact)

    psw = sub.add_parser(
        "serve-worker",
        help="standalone shard worker for cross-host serving: serve "
             "one doc-shard's /rpc surface on a fixed port so a "
             "router's static address grid can span hosts")
    psw.add_argument("index_dir")
    psw.add_argument("--shard", required=True, metavar="i/S",
                     help="this worker's shard index and the total "
                          "shard count, e.g. 0/4 (every worker and the "
                          "router derive the same doc partition)")
    psw.add_argument("--port", type=int, default=0,
                     help="listen port (0 = ephemeral, announced in "
                          "the ready JSON)")
    psw.add_argument("--host", default="127.0.0.1",
                     help="bind address; a cross-host worker must bind "
                          "a routable interface (0.0.0.0 or the host's "
                          "address) — the loopback default is only "
                          "reachable from the same machine")
    psw.add_argument("--replica", type=int, default=0,
                     help="replica index within the shard (identity "
                          "only; shown in /healthz)")
    psw.add_argument("--layout",
                     choices=["auto", "dense", "sparse"],
                     default="auto")
    psw.add_argument("--deadline", type=float, default=None,
                     help="per-request device dispatch deadline (s)")
    psw.add_argument("--concurrency", type=int, default=4,
                     help="admission: requests executing at once")
    psw.add_argument("--queue-depth", type=int, default=16,
                     help="admission: max requests waiting for a slot")
    psw.add_argument("--no-warm", action="store_true",
                     help="skip the compile-shape warm-up + residency "
                          "prewarm (faster start; the first requests "
                          "pay the compiles instead)")
    psw.add_argument("--run-for", type=float, default=None, metavar="S",
                     help="serve for S seconds then exit (default: "
                          "until SIGTERM/Ctrl-C)")
    _add_backend_arg(psw)
    psw.set_defaults(fn=cmd_serve_worker)

    pe = sub.add_parser("eval", help="score a trec_eval-format run file "
                                     "against qrels (MAP/MRR/NDCG@10/...)")
    pe.add_argument("run", help="run file (qid Q0 docid rank score tag)")
    pe.add_argument("qrels", help="qrels file (qid 0 docid rel)")
    pe.add_argument("--complete", action="store_true",
                    help="average over every qrels qid, scoring qids "
                         "missing from the run as zero (trec_eval -c)")
    pe.set_defaults(fn=cmd_eval)

    pp = sub.add_parser("pack", help="pack plain text into TREC format "
                                     "(one <DOC> per input line), or "
                                     "canonicalize trectext/trecweb corpora")
    pp.add_argument("text_file")
    pp.add_argument("output", help="TREC file to write")
    pp.add_argument("--prefix", default="LINE", help="docid prefix")
    pp.add_argument("--format", choices=["lines", "trectext", "trecweb"],
                    default="lines",
                    help="'trectext' keeps only the known section tags' "
                         "content; 'trecweb' parses <DOCHDR> records and "
                         "scrubs the URL")
    pp.set_defaults(fn=cmd_pack)

    pc = sub.add_parser("count", help="count documents in a corpus")
    pc.add_argument("corpus", nargs="+")
    pc.set_defaults(fn=cmd_count)

    pd = sub.add_parser(
        "docno", help="docid <-> docno mapping (TrecDocnoMapping CLI)")
    pd.add_argument("index_dir")
    pd.add_argument("op", choices=["list", "getDocno", "getDocid"])
    pd.add_argument("arg", nargs="?", default=None,
                    help="docid for getDocno, docno for getDocid")
    pd.set_defaults(fn=cmd_docno)

    pe = sub.add_parser("expand", help="wildcard term lookup (char-k-grams)")
    pe.add_argument("index_dir")
    pe.add_argument("pattern", help="glob pattern, e.g. 'te*' or '*tion'")
    pe.add_argument("--chargram-k", type=int, default=3)
    pe.add_argument("-n", type=int, default=50)
    pe.set_defaults(fn=cmd_expand)

    pl = sub.add_parser(
        "lint", help="static analysis: jit hazards, lock discipline, "
        "telemetry/env contracts, determinism/lowering hazards, and the "
        "shape-universe proof (pure AST, no JAX; RUNBOOK §13)")
    pl.add_argument("path", nargs="?", default=None,
                    help="package dir to analyze (default: the installed "
                         "tpu_ir package)")
    pl.add_argument("--json", action="store_true",
                    help="structured findings on stdout")
    pl.add_argument("--baseline", metavar="FILE", default=None,
                    help="grandfathered-findings file (default: "
                         "lint_baseline.json next to the package, if "
                         "present)")
    pl.add_argument("--fix-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                         "(an explicit, reviewable accept — new entries "
                         "get a TODO reason)")
    pl.add_argument("--rules", action="store_true",
                    help="include the rule catalog in --json output")
    pl.add_argument("--locks", action="store_true",
                    help="dump the whole-program lock inventory and "
                         "acquisition-order graph as JSON")
    pl.add_argument("--env-table", action="store_true",
                    help="print the generated RUNBOOK env-var table")
    pl.add_argument("--diff", nargs="?", const="HEAD", default=None,
                    metavar="REF",
                    help="restrict per-file findings to files changed vs "
                         "the git ref (default HEAD); package-level "
                         "contracts (TPU30x/TPU50x) stay whole-package — "
                         "the fast pre-commit mode (RUNBOOK §13)")
    pl.add_argument("--self-test", action="store_true",
                    help="run the seeded positive/negative rule fixtures "
                         "instead of linting (exit 1 if any rule stopped "
                         "catching what it claims to catch)")
    pl.set_defaults(fn=cmd_lint)

    args = p.parse_args(argv)
    from .faults import BuildError, IntegrityError

    try:
        return args.fn(args)
    except (ValueError, BuildError, IntegrityError) as e:
        # user-facing capability/usage errors (unknown layout, phrase query
        # on a v1 index, ...) and the fault layer's structured failures
        # (retry exhaustion, corrupt artifact) print a clean one-line
        # message, not a traceback
        print(f"error: {e}", file=sys.stderr)
        return 1
    except FileNotFoundError as e:
        # a missing artifact is a usage error ONLY for commands whose job
        # is loading artifacts the user named (expand on a --no-chargrams
        # index, search on a non-index dir). Builder-side commands are NOT
        # covered: there a FileNotFoundError means a bug (e.g. a temp file
        # that should exist) and must keep its traceback (ADVICE r5).
        if getattr(args.fn, "__name__", "") not in _ARTIFACT_ENTRY_CMDS:
            raise
        path = e.filename if e.filename else str(e)
        print(f"error: missing artifact: {path} (if this path is not an "
              "artifact you asked for, it is a bug — please report)",
              file=sys.stderr)
        return 1
    except BrokenPipeError:
        # downstream pipe (e.g. `| head`) closed early — standard unix exit;
        # handled here (not just under __main__) so the installed console
        # script gets the same behavior
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 141


if __name__ == "__main__":
    sys.exit(main())
