"""tpu_ir.obs — the unified telemetry layer (ISSUE 3).

One subsystem, three instruments, zero new dependencies:

- **Spans** (trace.py): `trace(name)` context managers building
  per-request / per-build span trees, held in a bounded ring of recent
  traces. `TPU_IR_TRACE=0` disables everything at one flag test.
- **Histograms** (histogram.py) + **registry** (registry.py): fixed
  log-bucket latency histograms and all process-wide counters
  (`recovery.*`, `serving.*`, `fault.*`) behind one
  `TelemetryRegistry.snapshot(reset=...)`.
- **Flight recorder** (recorder.py): on a soak invariant breach, breaker
  open, or structured build error, the last-N traces + a registry
  snapshot are dumped to a JSONL artifact — the JobTracker failure
  page, reborn.

ISSUE 4 adds the cluster-scope top layer:

- **Jobs** (progress.py): JobTracker-style job/phase progress tracking
  (`start_job` / `report_progress`), a bounded last-K job history.
- **Aggregation** (aggregate.py): serializable registry snapshots
  merged across processes — live via multihost collectives, post-mortem
  via the `TPU_IR_TELEMETRY_DIR` file spool.
- **HTTP server** (server.py): `/metrics`, `/healthz`, `/jobs`,
  `/flight` on a stdlib ThreadingHTTPServer
  (`tpu-ir serve-bench --metrics-port`, build `--track PORT`).

Scrape surfaces: `tpu-ir metrics` (JSON / Prometheus text; `--cluster`
for the spool-merged view), `tpu-ir trace-dump`, `tpu-ir stats`
(superset of the PR 2 shape), the latency sections of
`tpu-ir serve-bench` / `bench.py`, and the HTTP endpoints above.
RUNBOOK "Reading the telemetry" / "Live monitoring" are the operator's
guides.
"""

from . import progress
from .histogram import LatencyHistogram, bucket_index
from .progress import current_job, report_progress, start_job
from .recorder import flight_dir, flight_dump, reset_rate_limit
from .registry import (
    DECLARED_GAUGES,
    DECLARED_HISTOGRAMS,
    DISPATCH_STAGES,
    FAULT_SITES,
    GAUGE_MERGE,
    LOAD_STAGES,
    REQUEST_STAGES,
    SERVICE_LEVELS,
    SNAPSHOT_SCHEMA,
    TelemetryRegistry,
    get_registry,
)
from .trace import (
    Span,
    attach,
    clear_traces,
    configure,
    current_span,
    enabled,
    kernel_annotation,
    recent_traces,
    record_span,
    trace,
)
from . import profiling  # noqa: E402 — needs trace/registry bound above
from .profiling import (
    profile_report,
    profiled_jit,
    recompiles_last_60s,
    sample_memory,
)
from . import querylog  # noqa: E402 — needs recorder/registry bound above
from . import disttrace  # noqa: E402 — registers the root-close hook
from . import timeseries  # noqa: E402 — needs registry/recorder above


def reset_all() -> None:
    """Full telemetry reset: registry counters + histograms, the trace
    ring, the query log, the distributed-trace store + SLO windows, the
    job history, and the flight recorder's rate limiter. The
    test-isolation hook (tests/conftest.py autouse fixture) — one
    process-wide telemetry state must not leak between tests or between
    runs. (The registry's seq/resets stamps stay monotonic through this
    — that IS their contract.)"""
    get_registry().reset()
    clear_traces()
    progress.clear_jobs()
    reset_rate_limit()
    profiling.reset_profile()
    querylog.clear()
    disttrace.reset()
    timeseries.reset()


__all__ = [
    "LatencyHistogram", "bucket_index",
    "flight_dir", "flight_dump", "reset_rate_limit",
    "TelemetryRegistry", "get_registry", "SNAPSHOT_SCHEMA",
    "FAULT_SITES", "REQUEST_STAGES", "SERVICE_LEVELS",
    "DECLARED_HISTOGRAMS", "DECLARED_GAUGES", "DISPATCH_STAGES",
    "GAUGE_MERGE",
    "progress", "start_job", "report_progress", "current_job",
    "Span", "trace", "attach", "current_span", "recent_traces",
    "clear_traces", "configure", "enabled", "kernel_annotation",
    "record_span", "reset_all",
    "profiling", "profiled_jit", "profile_report", "sample_memory",
    "recompiles_last_60s",
    "querylog",
    "disttrace",
    "timeseries",
]
