"""`tpu-ir bench-check`: the BENCH_HISTORY.jsonl regression sentry.

BENCH_HISTORY.jsonl (bench.py appends one commit-stamped summary row per
run) was an append-only log: a regression landed as one more line nobody
diffed. This module turns the trajectory into an ENFORCED contract — the
newest row is compared against the trailing-window median of its
comparable predecessors, per metric, with noise-tolerant thresholds, and
a breach exits non-zero so CI (or an operator) sees it the run it lands.

Semantics:

- **comparable** rows share the newest row's (config, backend,
  build_only) key — a CPU-control build is never judged against TPU
  rows, nor msmarco quality rows against ref throughput rows.
- **window**: the last TPU_IR_BENCH_CHECK_WINDOW comparable rows before
  the newest; fewer than TPU_IR_BENCH_CHECK_MIN_ROWS of them is
  "insufficient history" (exit 2 — not a pass: the sentry must not
  claim a trajectory it cannot see; `--self-test` maps this to a clean
  skip so the gate can gate itself from day one).
- **metrics**: the curated METRICS table — each with a direction
  (higher/lower is better) and an absolute noise floor. Negative values
  are failure sentinels (-1.0) and are excluded on either side.
- **breach**: three conditions, ALL required — worse than the median
  by more than TPU_IR_BENCH_CHECK_TOLERANCE relative (default 30%),
  worse by more than the metric's absolute floor (so a 0.4 ms p50
  cannot breach on scheduler jitter), and OUTSIDE the window's
  observed envelope (worse than every prior windowed value). The
  envelope term is what makes the sentry honest on noisy hosts: the
  checked-in history shows ±40% run-to-run swings on IDENTICAL code
  (container weather), so "below the median" alone would cry wolf —
  a value the trajectory itself has already visited is weather, a
  value it has never been is a regression.

Exit codes (the CLI contract, test-pinned): 0 pass, 1 breach,
2 insufficient history / unreadable file.
"""

from __future__ import annotations

import json
import os

from ..utils import envvars

# metric -> (direction, absolute noise floor). Directions: "higher" =
# bigger is better (throughput, quality, bandwidth), "lower" = smaller
# is better (wall times, latencies, compile cost, memory peaks).
METRICS: dict[str, tuple[str, float]] = {
    # headline + throughput
    "value": ("higher", 0.0),
    "queries_per_sec": ("higher", 0.0),
    "tfidf_queries_per_sec": ("higher", 0.0),
    "bm25_queries_per_sec": ("higher", 0.0),
    "rerank_queries_per_sec": ("higher", 0.0),
    "top1000_queries_per_sec": ("higher", 0.0),
    # deep-k under block-max pruning (ISSUE 13): the warmed k=1000
    # rate (the tentpole's headline number — carried by the
    # pre-weighted strip cache) and the realized skip fraction. On the
    # current msmarco corpus the mask does not engage (entity+stopword
    # queries leave tau = 0; the checked-in baseline records 0.0), so
    # today this entry only TRACKS the fraction; it starts guarding
    # once rows record positive engagement (a later collapse to 0 then
    # breaches the envelope). The 0.05 floor absorbs corpus-shape
    # jitter in which blocks survive.
    "topk1000_qps": ("higher", 0.0),
    "blockmax_skip_block_fraction": ("higher", 0.05),
    "load_h2d_mbps": ("higher", 0.0),
    # quality (msmarco rows; the quality gate hard-fails, this trends)
    "rerank_ndcg_at_10": ("higher", 0.0),
    "bm25_mrr_at_10": ("higher", 0.0),
    "recall_at_10": ("higher", 0.0),
    "top1000_recall": ("higher", 0.0),
    # wall / latency
    "index_wall_s": ("lower", 1.0),
    "index_wall_s_cold": ("lower", 2.0),
    "query_p50_ms": ("lower", 2.0),
    # p99 of the 50-call REPL loop is a max-of-50: on the shared-host
    # containers the bench runs on, scheduler/GC spikes of 10-35 ms hit
    # it with profiling on OR off (measured) — the floor sits above
    # that weather band; the relative term still guards the TPU regime
    # where real p99 is ~100 ms+
    "query_p99_ms": ("lower", 50.0),
    "scorer_load_cold_s": ("lower", 1.0),
    "scorer_load_warm_s": ("lower", 1.0),
    "warm_index_load_s": ("lower", 1.0),
    "verify_s": ("lower", 0.5),
    # device-cost profiling (ISSUE 7 row fields)
    "compile_s": ("lower", 1.0),
    "warm_compile_s": ("lower", 1.0),
    "recompiles": ("lower", 2.0),
    "warm_recompiles": ("lower", 2.0),
    "device_time_ms": ("lower", 2.0),
    "warm_device_time_ms": ("lower", 2.0),
    "peak_hbm_bytes": ("lower", float(64 << 20)),
    "warm_peak_hbm_bytes": ("lower", float(64 << 20)),
    # coalesced serving (ISSUE 9 serve-sweep rows): throughput at the
    # sweep's largest concurrency, its tail latency (same max-of-N
    # weather floor as query_p99_ms), the solo-path p50 the bounded
    # coalescing wait must not regress, and median batch occupancy
    # (occupancy collapsing to ~1 means coalescing silently disengaged)
    "batched_qps": ("higher", 0.0),
    "batched_p99_ms": ("lower", 50.0),
    "solo_p50_ms": ("lower", 2.0),
    "batch_occupancy_mean": ("higher", 0.0),
    # scatter-gather serving (ISSUE 10 serve_routed rows): routed
    # throughput and tail (the same max-of-N weather floor), the
    # fraction of responses that shipped partial (more partials = more
    # shard loss — lower is better; small absolute floor so one extra
    # partial in a small soak is weather), and hedges fired (a hedging
    # regression shows as a sustained jump — floor absorbs run jitter)
    "routed_qps": ("higher", 0.0),
    "routed_p99_ms": ("lower", 50.0),
    "partial_fraction": ("lower", 0.05),
    "hedge_fired": ("lower", 5.0),
    # result-cache tier (ISSUE 15; per-skew serve_routed rows): the
    # realized exact-hit fraction under the row's workload shape — a
    # collapse means the cache silently disengaged (key drift, a
    # generation bump storm, capacity misconfig). The 0.05 floor
    # absorbs draw-to-draw jitter in which head queries repeat.
    "cache_hit_fraction": ("higher", 0.05),
    # elastic serving (ISSUE 16; serve_routed -autoscale rows): burst
    # p99 is the claim (served latency during the diurnal PEAK window —
    # the max-of-N weather floor of routed_p99_ms applies), scale
    # events trending UP means the dampers stopped damping (flapping),
    # and overprovision creeping up means the scaler buys replicas the
    # demand series never needed. Floors absorb one extra event / one
    # tick-accounting wobble per run.
    "burst_p99_ms": ("lower", 50.0),
    "scale_events": ("lower", 2.0),
    "overprovision_fraction": ("lower", 0.05),
    # predictive autoscaling (ISSUE 19; the forecast-vs-reactive A/B
    # inside serve_routed -autoscale rows): forecast_lead_s is how far
    # BEFORE the first diurnal crest the forecast-armed scaler fired
    # its first scale-up (higher = more predictive; the 0.5 s floor is
    # controller-tick + fit-refresh granularity), and the forecast
    # arm's burst p99 rides the same weather floor as the reactive one
    "forecast_lead_s": ("higher", 0.5),
    "forecast_burst_p99_ms": ("lower", 50.0),
    # streaming-build phase walls (ISSUE 11: wiki/build_scale rows) —
    # the radix restructure's whole point is driving pass2_combine_s
    # down, so the sentry gates each pass plus the end-to-end build
    # wall, direction-aware lower-is-better with per-phase noise floors
    # sized to container weather on second-scale builds
    "build_s": ("lower", 2.0),
    "pass1_tokenize_s": ("lower", 1.0),
    "pass2_combine_s": ("lower", 1.0),
    "pass3_reduce_s": ("lower", 1.0),
    # live-index generation swap (ISSUE 12 ingest_swap rows): the
    # widest gap between consecutive successful probe responses across
    # the swap window — zero-downtime means this is ordinary request
    # latency; a load-blocking swap regression shows as a seconds-scale
    # jump. Floor absorbs scheduler weather on shared CI hosts (the
    # probe loop is a max-of-N like the p99 metrics above).
    "swap_gap_ms": ("lower", 100.0),
    # reload-to-first-new-generation-response: dominated by the new
    # generation's load+warm, so the floor is generous — the metric
    # guards against an order-of-magnitude staleness regression, not ms
    "swap_staleness_ms": ("lower", 2000.0),
    # durable ingest (ISSUE 17 ingest_soak rows): sustained acked
    # docs/s through the WAL'd writer, mid-soak SIGKILL+recovery
    # included — the WAL's append/fsync cost and the replay wall both
    # live inside this number, so a durability regression shows here
    "ingest_docs_per_s": ("higher", 0.0),
    # median flush-commit -> first-query-served-from-that-data: the
    # freshness number ROADMAP item 2 asks for. Dominated by
    # compaction + generation reload on these corpora, so the floor is
    # generous like swap_staleness_ms — the sentry guards against an
    # order-of-magnitude staleness regression, not ms-level weather
    "freshness_lag_ms": ("lower", 2000.0),
    # compressed arena (ISSUE 20; bench --compress A/B rows): on-disk
    # part bytes and their per-doc normalization are the compression
    # claim itself — creeping back UP means the codec (or a new
    # section someone added) is leaking bytes. Floors absorb arena
    # alignment padding when shard counts shift between runs.
    "index_bytes": ("lower", 64 * 1024.0),
    "bytes_per_doc": ("lower", 1.0),
    # cold-load phase walls the compression directly buys: read_s
    # scales with bytes mmap-faulted off disk, h2d_s with bytes
    # shipped to the device (the bf16 strip halves its share).
    # Second-scale container IO weather needs real floors.
    "load_read_s": ("lower", 0.5),
    "load_h2d_s": ("lower", 0.5),
}


def _group_key(row: dict) -> tuple:
    return (row.get("config"), row.get("backend"),
            bool(row.get("build_only")))


def _metric_value(row: dict, name: str) -> float | None:
    v = row.get(name)
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    if v < 0:  # -1.0 = the bench's failed-measurement sentinel
        return None
    return float(v)


def _median(values: list[float]) -> float:
    s = sorted(values)
    n = len(s)
    return s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2.0


def read_history(path: str) -> list[dict]:
    """Parse the jsonl, skipping unparseable lines (a torn append must
    not wedge the gate forever). errors="replace": a partial multi-byte
    sequence from a killed writer must surface as a skipped line, not a
    UnicodeDecodeError out of the line iterator itself."""
    rows = []
    with open(path, encoding="utf-8", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue
            if isinstance(row, dict):
                rows.append(row)
    return rows


def check_history(rows: list[dict], *, window: int | None = None,
                  min_rows: int | None = None,
                  tolerance: float | None = None) -> dict:
    """THE sentry decision, pure on a parsed row list (tests feed
    synthetic histories). Returns {"status": "ok"|"breach"|
    "insufficient_history", "checked": N, "breaches": [...], ...}."""
    # clamp like the env declarations do (minimum=1/0.0): the CLI flags
    # bypass envvars validation, and prior[-0:] would silently select
    # the ENTIRE history instead of zero rows
    window = max(1, window if window is not None else envvars.get_int(
        "TPU_IR_BENCH_CHECK_WINDOW"))
    min_rows = max(1, min_rows if min_rows is not None else envvars.get_int(
        "TPU_IR_BENCH_CHECK_MIN_ROWS"))
    tolerance = max(0.0, tolerance if tolerance is not None
                    else envvars.get_float("TPU_IR_BENCH_CHECK_TOLERANCE"))
    if not rows:
        return {"status": "insufficient_history", "reason": "empty history",
                "rows": 0, "comparable": 0, "min_rows": min_rows}
    newest = rows[-1]
    key = _group_key(newest)
    prior = [r for r in rows[:-1] if _group_key(r) == key]
    windowed = prior[-window:]
    out: dict = {
        "config": newest.get("config"),
        "backend": newest.get("backend"),
        "build_only": bool(newest.get("build_only")),
        "commit": newest.get("commit"),
        "ts": newest.get("ts"),
        "rows": len(rows),
        "comparable": len(prior),
        "window": len(windowed),
        "min_rows": min_rows,
        "tolerance": tolerance,
    }
    if len(windowed) < min_rows:
        out["status"] = "insufficient_history"
        out["reason"] = (f"{len(windowed)} comparable prior row(s) for "
                         f"{key}, need {min_rows}")
        return out
    breaches, checked, skipped = [], [], []
    for name, (direction, floor) in sorted(METRICS.items()):
        new = _metric_value(newest, name)
        if new is None:
            continue
        past = [v for v in (_metric_value(r, name) for r in windowed)
                if v is not None]
        if len(past) < min_rows:
            skipped.append(name)
            continue
        med = _median(past)
        if direction == "higher":
            worse_by = med - new
            outside_envelope = new < min(past)
        else:
            worse_by = new - med
            outside_envelope = new > max(past)
        rel_limit = med * tolerance
        entry = {"metric": name, "value": new, "median": round(med, 4),
                 "direction": direction, "window": len(past)}
        checked.append(name)
        if worse_by > rel_limit and worse_by > floor and outside_envelope:
            entry["worse_by"] = round(worse_by, 4)
            breaches.append(entry)
    out["checked"] = checked
    out["skipped"] = skipped
    out["breaches"] = breaches
    out["status"] = "breach" if breaches else "ok"
    return out


def default_history_path() -> str | None:
    """BENCH_HISTORY.jsonl in the CWD, else next to the package (the
    checked-in repo file `--self-test` gates on)."""
    for cand in (
        os.path.join(os.getcwd(), "BENCH_HISTORY.jsonl"),
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))),
            "BENCH_HISTORY.jsonl"),
    ):
        if os.path.exists(cand):
            return cand
    return None


def append_history_row(row: dict, path: str | None = None) -> str | None:
    """Append one commit/timestamp-stamped summary row to
    BENCH_HISTORY.jsonl (the bench.py `_append_history` contract, shared
    so `tpu-ir serve-bench --concurrency N,N,...` sweep rows land in the
    same trajectory the sentry gates). Best-effort: a read-only checkout
    must not fail the run. Returns the path written, or None."""
    import subprocess
    import time

    path = path or default_history_path() or os.path.join(
        os.getcwd(), "BENCH_HISTORY.jsonl")
    try:
        commit = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(path) or ".",
            capture_output=True, text=True, timeout=10).stdout.strip()
    except (subprocess.SubprocessError, OSError):
        commit = ""
    stamped = {"ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
               "commit": commit or None, **row}
    try:
        with open(path, "a", encoding="utf-8") as f:
            f.write(json.dumps(stamped, default=repr) + "\n")
    except OSError:
        return None
    return path


def run_check(path: str | None = None, *, window: int | None = None,
              min_rows: int | None = None, tolerance: float | None = None,
              self_test: bool = False) -> tuple[int, dict]:
    """The CLI body: (exit_code, report). Exit 0 pass, 1 breach, 2
    insufficient history or unreadable file; `--self-test` downgrades
    insufficient history to a clean skip (exit 0), so the tier-1 gate
    can run against the young checked-in history and harden itself as
    rows accumulate — the lint self-check pattern."""
    path = path or default_history_path()
    if not path or not os.path.exists(path):
        report = {"status": "insufficient_history",
                  "reason": f"no history file ({path or 'not found'})"}
        return (0 if self_test else 2), report
    try:
        rows = read_history(path)
    except OSError as e:
        return (0 if self_test else 2), {
            "status": "insufficient_history",
            "reason": f"unreadable history: {e}"}
    report = check_history(rows, window=window, min_rows=min_rows,
                           tolerance=tolerance)
    report["history"] = path
    if report["status"] == "ok":
        return 0, report
    if report["status"] == "breach":
        return 1, report
    return (0 if self_test else 2), report
