"""Cross-process telemetry aggregation: N registries -> one cluster view.

A multi-host build runs N processes, each with its own TelemetryRegistry
— N disjoint counter/histogram sets nobody merged. Following the Dapper
split of cheap always-on collection from separate aggregation, this
module adds the aggregation half on top of the registry's serializable
raw snapshots (`TelemetryRegistry.collect_state()`: counters + raw
histogram bucket counts, stamped schema/seq/resets/run_id):

- `merge_snapshots(snaps)`: counters sum; histograms merge bucket-wise
  (the shared fixed bucket layout makes the merge exact, associative
  and commutative — property-pinned in tests). Yields the cluster-total
  view plus a per-process index.
- `gather_cluster(...)`: LIVE aggregation over the jax coordination
  service (`multihost_utils.process_allgather` of the JSON blob, the
  allgather_strings transport) when a distributed job is initialized —
  every process receives the same merged cluster snapshot.
- file spool (`TPU_IR_TELEMETRY_DIR`): POST-MORTEM aggregation — each
  process writes `telemetry-<host>-<pid>-<seq>.json` atomically;
  `read_spool()` keeps only the newest snapshot per run_id (snapshots
  are cumulative; merging two generations of one process would double
  count) and `merge_spool()` folds them. `SpoolWriter` is an optional
  background thread refreshing the spool on an interval, so a crashed
  process leaves a near-final record behind.

Scrape surfaces: `tpu-ir metrics --cluster` / `tpu-ir stats --cluster`
(spool merge from a fresh CLI process), and the multi-host build spools
its final snapshot when TPU_IR_TELEMETRY_DIR is set.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time

import numpy as np

from ..utils import envvars
from .histogram import NUM_BUCKETS, summary_from_counts
from .registry import SNAPSHOT_SCHEMA, get_registry


def local_snapshot(reset: bool = False) -> dict:
    """This process's serializable raw snapshot, stamped with identity
    (host, pid, and — when a distributed job is live — process index)."""
    snap = get_registry().collect_state(reset)
    snap["host"] = socket.gethostname()
    snap["pid"] = os.getpid()
    snap["time"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    try:  # only meaningful (and only cheap) once jax.distributed is up
        import jax

        snap["process_index"] = jax.process_index()
    except Exception:  # noqa: BLE001 — identity is best-effort garnish
        snap["process_index"] = 0
    return snap


def merge_snapshots(snaps: list) -> dict:
    """Fold N raw snapshots into the cluster view: counter totals are
    sums, histogram buckets add element-wise (exact — the fixed shared
    bucket layout is what makes distributed percentiles honest), gauges
    merge by their declared policy (registry.GAUGE_MERGE: "max" keeps
    the cluster-wide peak, "last" takes the newest snapshot's level —
    ordered by (time, seq, run_id), so the merge is deterministic under
    any input permutation), and the per-process identities ride along.
    Snapshots from an unknown future schema are rejected loudly rather
    than mis-summed."""
    from .registry import GAUGE_MERGE

    for s in snaps:
        if s.get("schema", 0) > SNAPSHOT_SCHEMA:
            raise ValueError(
                f"snapshot schema {s.get('schema')} is newer than this "
                f"build understands ({SNAPSHOT_SCHEMA}); upgrade tpu-ir")
    counters: dict[str, int] = {}
    hist_counts: dict[str, list] = {}
    hist_sums: dict[str, float] = {}
    gauges: dict[str, float] = {}
    # newest-last deterministic order for the last-wins gauge policy
    for s in sorted(snaps, key=lambda s: (s.get("time") or "",
                                          s.get("seq", 0),
                                          s.get("run_id") or "")):
        for k, v in s.get("gauges", {}).items():
            if GAUGE_MERGE.get(k) == "max":
                gauges[k] = max(gauges.get(k, float(v)), float(v))
            else:
                gauges[k] = float(v)
    for s in snaps:
        for k, v in s.get("counters", {}).items():
            counters[k] = counters.get(k, 0) + int(v)
        for name, h in s.get("histograms", {}).items():
            c = list(h["counts"])
            if len(c) != NUM_BUCKETS:
                raise ValueError(
                    f"histogram {name!r} has {len(c)} buckets, expected "
                    f"{NUM_BUCKETS} — mixed-version snapshots?")
            if name in hist_counts:
                hist_counts[name] = [a + b
                                     for a, b in zip(hist_counts[name], c)]
                hist_sums[name] += float(h["sum_s"])
            else:
                hist_counts[name] = c
                hist_sums[name] = float(h["sum_s"])
    return {
        "schema": SNAPSHOT_SCHEMA,
        "processes": len(snaps),
        "counters": counters,
        "gauges": gauges,
        "histograms": {n: summary_from_counts(c, hist_sums[n])
                       for n, c in sorted(hist_counts.items())},
        "per_process": [
            {"host": s.get("host"), "pid": s.get("pid"),
             "process_index": s.get("process_index"),
             "run_id": s.get("run_id"), "seq": s.get("seq"),
             "time": s.get("time"),
             "events": sum(s.get("counters", {}).values())}
            for s in snaps],
    }


# -- live aggregation (collectives) ----------------------------------------

ALLGATHER_CHUNK_BYTES = 4 << 20


def gather_cluster(reset: bool = False) -> dict:
    """Merge every process's live snapshot across the distributed job.

    Single-process: merge_snapshots([local]). Multi-process: each
    process serializes its snapshot as a JSON blob and the blobs cross
    via `multihost_utils.process_allgather` in fixed-size uint8 rounds
    (the allgather_strings transport — snapshots are KBs, so this is
    one round in practice), after which EVERY process holds the same
    cluster view. All processes must call this together (it is a
    collective); `reset=True` drains every registry in the same
    exchange, so a per-interval cluster scrape loses nothing."""
    import jax

    local = local_snapshot(reset)
    if jax.process_count() == 1:
        return merge_snapshots([local])
    from jax.experimental import multihost_utils

    blob = json.dumps(local, default=repr).encode("utf-8")
    n = len(blob)
    sizes = np.asarray(multihost_utils.process_allgather(
        np.int64(n))).reshape(-1)
    max_n = int(sizes.max())
    bufs = [b""] * len(sizes)
    for ofs in range(0, max_n, ALLGATHER_CHUNK_BYTES):
        width = min(ALLGATHER_CHUNK_BYTES, max_n - ofs)
        chunk = np.zeros(width, np.uint8)
        if ofs < n:
            piece = blob[ofs : ofs + width]
            chunk[: len(piece)] = np.frombuffer(piece, np.uint8)
        gathered = np.asarray(multihost_utils.process_allgather(chunk))
        for p in range(len(sizes)):
            valid = max(0, min(int(sizes[p]) - ofs, width))
            if valid:
                bufs[p] += bytes(gathered[p, :valid])
    return merge_snapshots(
        [json.loads(b.decode("utf-8")) for b in bufs])


# -- the file spool (post-mortem aggregation) ------------------------------


def spool_dir() -> str | None:
    """The telemetry spool directory, or None when spooling is off."""
    return envvars.get_str("TPU_IR_TELEMETRY_DIR")


def spool_write(out_dir: str | None = None) -> str | None:
    """Write this process's snapshot into the spool (atomic: temp +
    rename, so a reader never sees a torn file). Returns the path, or
    None when no spool dir is configured. Never raises — spooling is
    telemetry, and a full disk must not fail the build it observes."""
    d = out_dir or spool_dir()
    if not d:
        return None
    try:
        os.makedirs(d, exist_ok=True)
        snap = local_snapshot()
        path = os.path.join(
            d, f"telemetry-{snap['host']}-{snap['pid']}-"
               f"{snap['seq']:06d}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(snap, f, default=repr)
        os.replace(tmp, path)
        # one live file per process lifetime: drop this run's older
        # generations so the spool stays bounded under a SpoolWriter
        prefix = f"telemetry-{snap['host']}-{snap['pid']}-"
        for name in os.listdir(d):
            if (name.startswith(prefix) and name.endswith(".json")
                    and os.path.join(d, name) != path):
                try:
                    os.unlink(os.path.join(d, name))
                except OSError:
                    pass
        return path
    except Exception:  # noqa: BLE001 — see docstring
        return None


def read_spool(out_dir: str | None = None) -> list:
    """Parse every spooled snapshot, keeping only the NEWEST (highest
    seq) per run_id — snapshots are cumulative, so merging two
    generations of one process would double count its events."""
    d = out_dir or spool_dir()
    if not d or not os.path.isdir(d):
        return []
    best: dict[str, dict] = {}
    for name in sorted(os.listdir(d)):
        if not (name.startswith("telemetry-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(d, name)) as f:
                snap = json.load(f)
        except (OSError, ValueError):
            continue
        key = snap.get("run_id") or name
        if key not in best or snap.get("seq", 0) > best[key].get("seq", 0):
            best[key] = snap
    return list(best.values())


def merge_spool(out_dir: str | None = None,
                include_local: bool = False) -> dict:
    """The post-mortem cluster view: fold the spool (optionally folding
    this process's live registry in too — for a process that is itself
    part of the cluster rather than a fresh CLI scraper). The local
    snapshot DISPLACES this process's own spooled generation (same
    run_id, dedup by highest seq): a serving process that both spools
    and answers /cluster must count itself exactly once."""
    snaps = read_spool(out_dir)
    if include_local or not snaps:
        local = local_snapshot()
        snaps = [s for s in snaps
                 if s.get("run_id") != local["run_id"]] + [local]
    return merge_snapshots(snaps)


# -- the span spool (distributed-trace post-mortem assembly) ---------------
#
# A second file family in the same TPU_IR_TELEMETRY_DIR:
# `spans-<host>-<pid>-<seq>.json`, each an APPEND-ONLY batch of
# completed span records {trace_id, span_id, parent_id, name, ...}
# (obs/disttrace.py's export format). Unlike the cumulative telemetry
# snapshots above — where only the newest file per run_id is truthful —
# span batches are disjoint events: the reader folds EVERY file. The
# writer bounds the family per process (oldest batches deleted past
# _SPAN_SPOOL_KEEP), the bounded-ring discipline on disk.

_SPAN_SPOOL_KEEP = 64
_span_spool_lock = threading.Lock()
_span_spool_seq = 0


def span_spool_write(spans: list, out_dir: str | None = None
                     ) -> str | None:
    """Append one batch of completed span records to the spool (atomic
    temp+rename per batch file). Returns the path, or None when no
    spool dir is configured or the batch is empty. Never raises."""
    d = out_dir or spool_dir()
    if not d or not spans:
        return None
    global _span_spool_seq
    try:
        os.makedirs(d, exist_ok=True)
        host = socket.gethostname()
        pid = os.getpid()
        with _span_spool_lock:
            _span_spool_seq += 1
            seq = _span_spool_seq
        path = os.path.join(d, f"spans-{host}-{pid}-{seq:06d}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"host": host, "pid": pid, "seq": seq,
                       "spans": spans}, f, default=repr)
        os.replace(tmp, path)
        # bound the family per process: keep the newest K batches
        prefix = f"spans-{host}-{pid}-"
        mine = sorted(n for n in os.listdir(d)
                      if n.startswith(prefix) and n.endswith(".json"))
        for name in mine[:-_SPAN_SPOOL_KEEP]:
            try:
                os.unlink(os.path.join(d, name))
            except OSError:
                pass
        return path
    except Exception:  # noqa: BLE001 — spooling must not fail serving
        return None


def read_span_spool(out_dir: str | None = None,
                    trace_id: str | None = None) -> list:
    """Every spooled span record (optionally filtered to one trace),
    across ALL batch files of all processes — batches are disjoint
    events, so unlike read_spool there is no newest-wins dedup."""
    d = out_dir or spool_dir()
    if not d or not os.path.isdir(d):
        return []
    out = []
    for name in sorted(os.listdir(d)):
        if not (name.startswith("spans-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(d, name)) as f:
                batch = json.load(f)
        except (OSError, ValueError):
            continue
        for rec in batch.get("spans", ()):
            if trace_id is None or rec.get("trace_id") == trace_id:
                out.append(rec)
    return out


class SpoolWriter:
    """Background thread refreshing this process's spool file on an
    interval, so a crash leaves a near-final record for the post-mortem
    merge. The thread is named under the 'tpu-ir-obs' prefix the test
    harness's leak guard watches — stop() is mandatory, daemonhood is
    only the crash backstop."""

    def __init__(self, out_dir: str | None = None,
                 interval_s: float | None = None):
        self._dir = out_dir or spool_dir()
        self._interval = (interval_s if interval_s is not None
                          else envvars.get_float("TPU_IR_SPOOL_INTERVAL"))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "SpoolWriter":
        if self._dir and self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="tpu-ir-obs-spool", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            spool_write(self._dir)
            self._spool_timeseries()

    def _spool_timeseries(self) -> None:
        # the history rides the same spool cadence (ISSUE 19): one
        # timeseries-<host>-<pid>.json per process, newest state wins,
        # merged by /timeseries?cluster=1 the way merge_spool folds the
        # telemetry snapshots
        try:
            from . import timeseries

            timeseries.spool_write_store(self._dir)
        except Exception:  # noqa: BLE001 — spooling is best-effort
            pass

    def stop(self) -> None:
        """Stop the thread and write one final snapshot (the authoritative
        end-of-run record)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        spool_write(self._dir)
        self._spool_timeseries()
