"""Distributed request tracing + the SLO burn-rate tracker (ISSUE 18).

The per-process tracer (obs/trace.py) builds one span tree per request
— but the serving path is now distributed: a routed query crosses
router -> N shard workers (hedges, failovers, generation groups) -> a
coalesced batch whose leader dispatches for followers. Those hops
produce disconnected trees in separate rings with no join key. This
module is the join key and the assembly:

- **Context** (`TraceContext`): W3C-traceparent-style triplet —
  trace_id (32 hex), span_id (16 hex), flags — minted at router
  admission (`mint()`), serialized as `00-<trace>-<span>-<flags>`
  (`to_header()`), carried as a `traceparent` header through
  `shardset.rpc_post`, and adopted (`adopt()`) by the worker's
  `/rpc/*` handler. `use(ctx)` installs a context thread-locally;
  `child(ctx)` derives a per-attempt context so a worker's spans
  parent under the exact RPC attempt that carried them.
- **Records**: flat span dicts `{trace_id, span_id, parent_id, name,
  service, host, pid, start_ms, dur_ms, attrs}` in a bounded
  per-process store keyed by trace_id. The store fills three ways:
  a root-close hook on obs/trace.py flattens each finished local tree
  under the installed context; `add_span()` records externally-timed
  regions (the router's RPC attempts, the coalescer's shared dispatch
  + re-parented slots); `ingest_remote()` folds span batches a worker
  piggybacked on its RPC response (`_trace` key) — live stitching.
- **Export**: kept traces spool as `spans-<host>-<pid>-<seq>.json`
  batches (obs/aggregate.py) — disjoint events, unlike the cumulative
  telemetry snapshots — so `tpu-ir trace <id>` assembles the waterfall
  post-mortem from TPU_IR_TELEMETRY_DIR alone.
- **Tail sampling**: the MINTING process decides at root close — keep
  100% of slow (>= TPU_IR_SLO_P99_MS) / partial / degraded / hedged /
  error roots (TPU_IR_TRACE_TAIL), 1-in-TPU_IR_TRACE_SAMPLE of the
  rest; an ADOPTED context always keeps + exports (the verdict belongs
  to the minter — a worker must not drop spans the router will keep).
- **SLO tracker**: every finished request classifies good/bad against
  TPU_IR_SLO_P99_MS and the availability target; two sliding windows
  (fast/slow) yield budget-burn multiples, exposed at `/slo`, gauged
  (slo.burn_fast/slow), fed to the Autoscaler as a second scale-up
  signal, and flight-recorded (`slo_burn_breach`) when BOTH windows
  burn past threshold — the multi-window rule that a single spike
  cannot page.

TPU_IR_DISTTRACE=0 turns the whole layer into flag tests returning
None/no-ops (pinned <= 1% alongside trace.py's discipline).
"""

from __future__ import annotations

import collections
import os
import socket
import threading
import time

from ..utils import envvars
from .recorder import flight_dump
from .registry import get_registry
from .trace import add_root_hook as _add_root_hook

_lock = threading.Lock()
_tls = threading.local()
_HOST = socket.gethostname()

_ENABLED = envvars.get_bool("TPU_IR_DISTTRACE")
_TAIL = envvars.get_bool("TPU_IR_TRACE_TAIL")
_SAMPLE_N = envvars.get_int("TPU_IR_TRACE_SAMPLE")
_SLO_MS = envvars.get_float("TPU_IR_SLO_P99_MS")

# store bounds: oldest whole trace evicted past _MAX_TRACES; spans past
# _MAX_SPANS_PER_TRACE count disttrace.spans_dropped (bounded rings as
# ever — a runaway fan-out must not grow the store without bound)
_MAX_TRACES = 256
_MAX_SPANS_PER_TRACE = 512

_SERVICE = "proc"
_root_seq = 0

# trace_id -> {"spans": [rec...], "local": [bool...], "exported": int}
# insertion-ordered so eviction drops the oldest trace whole
_STORE: "collections.OrderedDict[str, dict]" = collections.OrderedDict()


def enabled() -> bool:
    return _ENABLED


def set_service(name: str) -> None:
    """Label this process's span records (router / worker-s0r1 / ...) —
    the waterfall's lane names."""
    global _SERVICE
    _SERVICE = str(name)


def configure(enabled: bool | None = None, tail: bool | None = None,
              sample: int | None = None, slo_ms: float | None = None,
              slo_target: float | None = None,
              burn_threshold: float | None = None,
              min_samples: int | None = None,
              fast_window_s: float | None = None,
              slow_window_s: float | None = None,
              max_traces: int | None = None) -> None:
    """Runtime overrides of the env knobs (tests, REPLs) — the
    obs.trace.configure idiom."""
    global _ENABLED, _TAIL, _SAMPLE_N, _SLO_MS, _MAX_TRACES
    global _SLO_TARGET, _BURN_THRESHOLD, _MIN_SAMPLES
    if enabled is not None:
        _ENABLED = enabled
    if tail is not None:
        _TAIL = tail
    if sample is not None:
        _SAMPLE_N = max(1, sample)
    if slo_ms is not None:
        _SLO_MS = max(1.0, slo_ms)
    if slo_target is not None:
        _SLO_TARGET = min(max(slo_target, 0.0), 0.99999)
    if burn_threshold is not None:
        _BURN_THRESHOLD = max(0.0, burn_threshold)
    if min_samples is not None:
        _MIN_SAMPLES = max(1, min_samples)
    if fast_window_s is not None:
        _fast.horizon = max(0.001, fast_window_s)
    if slow_window_s is not None:
        _slow.horizon = max(0.001, slow_window_s)
    if max_traces is not None:
        _MAX_TRACES = max(1, max_traces)


def slo_p99_ms() -> float:
    return _SLO_MS


# -- the context -----------------------------------------------------------


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


def new_span_id() -> str:
    """A fresh 8-byte span id — for callers that pre-allocate an id
    shared across records (the coalescer's dispatch span appears once
    per member trace under the SAME id: the batch_id join)."""
    return _new_id(8)


class TraceContext:
    """One hop's identity in a distributed trace: which trace this is
    (trace_id), which span the NEXT records parent under (span_id), the
    W3C flags byte, and — for adopted contexts — the remote parent span
    the root links back to."""

    __slots__ = ("trace_id", "span_id", "parent_id", "flags", "adopted")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: str | None = None, flags: int = 1,
                 adopted: bool = False):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.flags = flags
        self.adopted = adopted

    def to_header(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{self.flags:02x}"

    def __repr__(self) -> str:
        return (f"TraceContext({self.to_header()!r}"
                f"{', adopted' if self.adopted else ''})")


def mint() -> TraceContext | None:
    """A fresh trace born HERE (router admission, or an unrouted
    frontend search). None when disttrace is disabled."""
    if not _ENABLED:
        return None
    get_registry().incr("disttrace.minted")
    return TraceContext(_new_id(16), _new_id(8))


def parse_traceparent(value: str | None):
    """`(trace_id, span_id, flags)` from a traceparent header, or None
    for anything malformed — a bad header degrades to untraced, never
    to a failed request."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) != 4 or parts[0] != "00":
        return None
    tid, sid, fl = parts[1], parts[2], parts[3]
    if len(tid) != 32 or len(sid) != 16 or len(fl) != 2:
        return None
    try:
        int(tid, 16)
        int(sid, 16)
        flags = int(fl, 16)
    except ValueError:
        return None
    if tid == "0" * 32 or sid == "0" * 16:
        return None
    return tid, sid, flags


def adopt(header: str | None) -> TraceContext | None:
    """Join a trace minted elsewhere: the incoming span_id becomes this
    process's parent, and a fresh span_id identifies the local root.
    Adopted traces always export — the sampling verdict is the
    minter's."""
    if not _ENABLED:
        return None
    parsed = parse_traceparent(header)
    if parsed is None:
        return None
    tid, parent, flags = parsed
    get_registry().incr("disttrace.adopted")
    return TraceContext(tid, _new_id(8), parent_id=parent, flags=flags,
                        adopted=True)


def child(ctx: TraceContext | None) -> TraceContext | None:
    """A per-attempt derived context: same trace, fresh span_id,
    parented under `ctx` — so a worker's spans land under the exact RPC
    attempt that carried them, not the request root."""
    if ctx is None:
        return None
    return TraceContext(ctx.trace_id, _new_id(8),
                        parent_id=ctx.span_id, flags=ctx.flags,
                        adopted=ctx.adopted)


class use:
    """Install `ctx` as this thread's current context (None is a free
    no-op — callers need no branch on the disabled path)."""

    __slots__ = ("_ctx", "_saved")

    def __init__(self, ctx: TraceContext | None):
        self._ctx = ctx
        self._saved = None

    def __enter__(self) -> TraceContext | None:
        if self._ctx is not None:
            self._saved = getattr(_tls, "ctx", None)
            _tls.ctx = self._ctx
        return self._ctx

    def __exit__(self, *exc) -> bool:
        if self._ctx is not None:
            _tls.ctx = self._saved
        return False


def current() -> TraceContext | None:
    return getattr(_tls, "ctx", None)


def current_trace_id() -> str | None:
    """The open request's trace id on this thread (the flight-record /
    querylog join key), or None."""
    ctx = getattr(_tls, "ctx", None)
    return ctx.trace_id if ctx is not None else None


# -- the span store --------------------------------------------------------


def _store_add(trace_id: str, rec: dict, local: bool) -> bool:
    dropped = False
    with _lock:
        entry = _STORE.get(trace_id)
        if entry is None:
            while len(_STORE) >= _MAX_TRACES:
                _STORE.popitem(last=False)
            entry = _STORE[trace_id] = {"spans": [], "local": [],
                                        "exported": 0}
        if len(entry["spans"]) >= _MAX_SPANS_PER_TRACE:
            dropped = True
        else:
            entry["spans"].append(rec)
            entry["local"].append(bool(local))
    if dropped:
        get_registry().incr("disttrace.spans_dropped")
    return not dropped


def add_span(trace_id: str | None, name: str, *,
             span_id: str | None = None, parent_id: str | None = None,
             start_ms: float | None = None, dur_ms: float = 0.0,
             attrs: dict | None = None, error: str | None = None,
             local: bool = True) -> str | None:
    """Record one externally-timed span (the router's RPC attempts, the
    coalescer's dispatch/slot spans). Returns the span_id (caller keeps
    it to `annotate` later: winner/loser/cancelled verdicts arrive
    after the span closed), or None when disabled."""
    if not _ENABLED or not trace_id:
        return None
    sid = span_id or _new_id(8)
    rec = {"trace_id": trace_id, "span_id": sid, "parent_id": parent_id,
           "name": name, "service": _SERVICE, "host": _HOST,
           "pid": os.getpid(),
           "start_ms": float(start_ms if start_ms is not None
                             else time.time() * 1000.0),
           "dur_ms": round(float(dur_ms), 3),
           "attrs": dict(attrs or {})}
    if error:
        rec["error"] = error
    _store_add(trace_id, rec, local)
    return sid


def annotate(trace_id: str | None, span_id: str | None,
             dur_ms: float | None = None, **attrs) -> None:
    """Late-bind attrs (and optionally the true duration) onto a
    stored span — how the router marks which attempt won, which lost,
    which was cancelled: attempt spans record at SUBMIT, and the
    verdict only exists at harvest."""
    if not _ENABLED or not trace_id or not span_id:
        return
    with _lock:
        entry = _STORE.get(trace_id)
        if entry is None:
            return
        for rec in entry["spans"]:
            if rec["span_id"] == span_id:
                if dur_ms is not None:
                    rec["dur_ms"] = round(float(dur_ms), 3)
                rec["attrs"].update(attrs)
                return


def ingest_remote(spans) -> None:
    """Fold a remote process's span batch (an RPC response's `_trace`
    piggyback) into the local store — live stitching, no spool walk."""
    if not _ENABLED or not spans:
        return
    for rec in spans:
        if isinstance(rec, dict) and rec.get("trace_id"):
            _store_add(rec["trace_id"], dict(rec), local=False)


def spans_for(trace_id: str, local_only: bool = False) -> list:
    """Copies of one trace's stored records (attr dicts copied too —
    `annotate` mutates in place and readers serialize concurrently)."""
    with _lock:
        entry = _STORE.get(trace_id)
        if entry is None:
            return []
        pairs = list(zip(entry["spans"], entry["local"]))
    return [dict(r, attrs=dict(r["attrs"])) for r, loc in pairs
            if loc or not local_only]


def trace_ids() -> list:
    """Stored trace ids, oldest first."""
    with _lock:
        return list(_STORE)


def drop(trace_id: str) -> None:
    with _lock:
        _STORE.pop(trace_id, None)


def piggyback(trace_id: str | None) -> list | None:
    """Worker-side export: this process's OWN spans for one trace,
    shipped on the RPC response (`_trace` key) so the router stitches
    live. Remote-ingested records are excluded — they already live
    where they were born."""
    if not _ENABLED or not trace_id:
        return None
    batch = spans_for(trace_id, local_only=True)
    if not batch:
        return None
    get_registry().incr("disttrace.spans_exported", len(batch))
    return batch


def _export_spool(trace_id: str) -> None:
    """Spool this trace's not-yet-exported LOCAL records (post-mortem
    assembly). Remote records stay out: their owning process spools
    them, and double-spooled spans would double-count a waterfall."""
    with _lock:
        entry = _STORE.get(trace_id)
        if entry is None:
            return
        local = [dict(r, attrs=dict(r["attrs"]))
                 for r, loc in zip(entry["spans"], entry["local"]) if loc]
        batch = local[entry["exported"]:]
        entry["exported"] = len(local)
    if not batch:
        return
    from .aggregate import span_spool_write

    if span_spool_write(batch) is not None:
        get_registry().incr("disttrace.spans_exported", len(batch))


# -- the root-close hook (obs/trace.py -> records) -------------------------


def _flatten(root, ctx: TraceContext) -> list:
    """One finished local span tree -> flat records under `ctx`: the
    root takes the context's OWN span_id (remote children minted from
    this context already point at it) and links to the remote parent
    when adopted; descendants get fresh ids."""
    root_wall_ms = (root.wall_time or time.time()
                    - root.dur_ns / 1e9) * 1000.0
    out = []

    def walk(span, parent_id, sid):
        rec = {"trace_id": ctx.trace_id, "span_id": sid,
               "parent_id": parent_id, "name": span.name,
               "service": _SERVICE, "host": _HOST, "pid": os.getpid(),
               "start_ms": round(root_wall_ms
                                 + (span.start_ns - root.start_ns) / 1e6,
                                 3),
               "dur_ms": round(span.dur_ns / 1e6, 3),
               "attrs": dict(span.attrs)}
        if span.error is not None:
            rec["error"] = span.error
        out.append(rec)
        for c in tuple(span.children):
            walk(c, sid, _new_id(8))

    walk(root, ctx.parent_id, ctx.span_id)
    return out


def _is_tail(root) -> bool:
    """The force-keep rule: slow, partial, degraded, hedged, shed or
    errored roots are the traces a post-mortem NEEDS — sampling never
    touches them."""
    if root.dur_ns / 1e6 >= _SLO_MS or root.error is not None:
        return True
    a = root.attrs
    return bool(a.get("partial") or a.get("degraded") or a.get("hedges")
                or a.get("shed"))


def _on_root_close(root) -> None:
    """trace.py fires this with EVERY completed root span (before ring
    sampling). Under an installed context: flatten, then the keep/drop
    verdict — adopted contexts always keep + export; minted ones apply
    the tail rule, then the 1-in-N dice."""
    if not _ENABLED:
        return
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        return
    for rec in _flatten(root, ctx):
        _store_add(ctx.trace_id, rec, local=True)
    if ctx.adopted:
        _export_spool(ctx.trace_id)
        return
    reg = get_registry()
    if _TAIL and _is_tail(root):
        reg.incr("disttrace.kept_tail")
    else:
        global _root_seq
        with _lock:
            _root_seq += 1
            kept = _root_seq % max(1, _SAMPLE_N) == 0
        if not kept:
            reg.incr("disttrace.dropped_sampled")
            drop(ctx.trace_id)
            return
        reg.incr("disttrace.kept_sampled")
    _export_spool(ctx.trace_id)


# -- stitching -------------------------------------------------------------


def stitch(trace_id: str, include_spool: bool = True) -> dict | None:
    """Assemble ONE trace's waterfall: the local store (live records +
    RPC piggybacks) merged with the span spool (post-mortem), deduped
    by span_id (a piggybacked span also spools at its birthplace),
    tree-built by parent_id. Returns None for an unknown trace."""
    t0 = time.perf_counter()
    spans = spans_for(trace_id)
    seen = {r["span_id"] for r in spans}
    if include_spool:
        from .aggregate import read_span_spool

        for rec in read_span_spool(trace_id=trace_id):
            sid = rec.get("span_id")
            if sid and sid not in seen:
                spans.append(rec)
                seen.add(sid)
    if not spans:
        return None
    by_id = {r["span_id"]: dict(r, children=[]) for r in spans}
    roots = []
    for node in by_id.values():
        p = node.get("parent_id")
        if p and p in by_id:
            by_id[p]["children"].append(node)
        else:
            roots.append(node)
    for node in by_id.values():
        node["children"].sort(key=lambda c: c.get("start_ms", 0.0))
    roots.sort(key=lambda r: r.get("start_ms", 0.0))
    start = min(r.get("start_ms", 0.0) for r in spans)
    end = max(r.get("start_ms", 0.0) + r.get("dur_ms", 0.0)
              for r in spans)
    reg = get_registry()
    reg.incr("disttrace.stitched")
    reg.observe("disttrace.stitch", time.perf_counter() - t0)
    return {"trace_id": trace_id, "span_count": len(spans),
            "start_ms": start, "dur_ms": round(end - start, 3),
            "services": sorted({r.get("service", "?") for r in spans}),
            "roots": roots}


# -- the SLO burn-rate tracker ---------------------------------------------

_SLO_TARGET = 0.99       # availability target: 1% error budget
_BURN_THRESHOLD = 10.0   # burn multiple that (in BOTH windows) breaches
_MIN_SAMPLES = 20        # fast-window floor before a breach can fire
_SLO_EVENT_CAP = 100_000


class _Window:
    """One sliding good/bad window: append-and-evict, O(evicted)."""

    __slots__ = ("horizon", "events", "bad")

    def __init__(self, horizon: float):
        self.horizon = horizon
        self.events: collections.deque = collections.deque()
        self.bad = 0

    def add(self, t: float, good: bool) -> None:
        self.events.append((t, good))
        if not good:
            self.bad += 1
        while len(self.events) > _SLO_EVENT_CAP:
            self._pop()
        self.evict(t)

    def _pop(self) -> None:
        _, g = self.events.popleft()
        if not g:
            self.bad -= 1

    def evict(self, now: float) -> None:
        cutoff = now - self.horizon
        while self.events and self.events[0][0] < cutoff:
            self._pop()

    def stats(self, now: float):
        self.evict(now)
        return len(self.events), self.bad


_slo_lock = threading.Lock()
_fast = _Window(60.0)
_slow = _Window(300.0)
_slo_levels: dict = {}
_breached = False


def _burn(n: int, bad: int) -> float:
    if not n:
        return 0.0
    budget = max(1e-9, 1.0 - _SLO_TARGET)
    return (bad / n) / budget


def slo_record(level: str, total_ms: float, ok: bool = True,
               classification: str = "full") -> bool:
    """Classify one finished request: GOOD iff it was served at full
    quality within TPU_IR_SLO_P99_MS — a shed, errored, partial or
    degraded response burns budget no matter how fast it was. Returns
    the verdict. Fires the budget-burn breach (flight record + counter)
    on the NOT-breached -> breached transition when both windows burn
    past threshold."""
    good = (bool(ok) and classification == "full"
            and float(total_ms) <= _SLO_MS)
    reg = get_registry()
    reg.incr("slo.good" if good else "slo.bad")
    now = time.monotonic()
    global _breached
    fire = False
    with _slo_lock:
        _fast.add(now, good)
        _slow.add(now, good)
        g, b = _slo_levels.get(level, (0, 0))
        _slo_levels[level] = (g + int(good), b + int(not good))
        fn, fb = _fast.stats(now)
        sn, sb = _slow.stats(now)
        burn_fast, burn_slow = _burn(fn, fb), _burn(sn, sb)
        breach = (fn >= _MIN_SAMPLES and burn_fast >= _BURN_THRESHOLD
                  and burn_slow >= _BURN_THRESHOLD)
        if breach and not _breached:
            _breached = True
            fire = True
        elif not breach:
            _breached = False
    reg.set_gauge("slo.burn_fast", round(burn_fast, 4))
    reg.set_gauge("slo.burn_slow", round(burn_slow, 4))
    if fire:
        reg.incr("slo.burn_breach")
        flight_dump("slo_burn_breach", extra=lambda: {"slo":
                                                      slo_snapshot()})
    return good


def slo_burn_signal() -> float:
    """The fast window's current burn multiple — the Autoscaler's
    second input signal (>= its slo_burn_up arms scale-up the way
    sustained occupancy does)."""
    with _slo_lock:
        n, bad = _fast.stats(time.monotonic())
        return round(_burn(n, bad), 4)


def slo_snapshot() -> dict:
    """The /slo payload: config, both windows' good/bad split and burn
    multiples, per-level lifetime split, breach state."""
    reg = get_registry()
    now = time.monotonic()
    with _slo_lock:
        fn, fb = _fast.stats(now)
        sn, sb = _slow.stats(now)
        levels = {lv: {"good": g, "bad": b}
                  for lv, (g, b) in sorted(_slo_levels.items())}
        breached = _breached
        fast_h, slow_h = _fast.horizon, _slow.horizon
    return {
        "slo_p99_ms": _SLO_MS,
        "target": _SLO_TARGET,
        "error_budget": round(1.0 - _SLO_TARGET, 6),
        "burn_threshold": _BURN_THRESHOLD,
        "breached": breached,
        "windows": {
            "fast": {"horizon_s": fast_h, "total": fn, "bad": fb,
                     "bad_fraction": round(fb / fn, 4) if fn else 0.0,
                     "burn": round(_burn(fn, fb), 4)},
            "slow": {"horizon_s": slow_h, "total": sn, "bad": sb,
                     "bad_fraction": round(sb / sn, 4) if sn else 0.0,
                     "burn": round(_burn(sn, sb), 4)},
        },
        "levels": levels,
        "good": reg.get("slo.good"),
        "bad": reg.get("slo.bad"),
        "breaches": reg.get("slo.burn_breach"),
    }


# -- lifecycle -------------------------------------------------------------


def reset() -> None:
    """Drop every trace + SLO window AND restore the env-derived config
    (test isolation via obs.reset_all — a test's configure() override
    must not leak into its neighbors)."""
    global _ENABLED, _TAIL, _SAMPLE_N, _SLO_MS, _MAX_TRACES, _SERVICE
    global _SLO_TARGET, _BURN_THRESHOLD, _MIN_SAMPLES
    global _root_seq, _breached
    with _lock:
        _STORE.clear()
        _root_seq = 0
    with _slo_lock:
        _fast.events.clear()
        _fast.bad = 0
        _fast.horizon = 60.0
        _slow.events.clear()
        _slow.bad = 0
        _slow.horizon = 300.0
        _slo_levels.clear()
        _breached = False
    _ENABLED = envvars.get_bool("TPU_IR_DISTTRACE")
    _TAIL = envvars.get_bool("TPU_IR_TRACE_TAIL")
    _SAMPLE_N = envvars.get_int("TPU_IR_TRACE_SAMPLE")
    _SLO_MS = envvars.get_float("TPU_IR_SLO_P99_MS")
    _MAX_TRACES = 256
    _SERVICE = "proc"
    _SLO_TARGET = 0.99
    _BURN_THRESHOLD = 10.0
    _MIN_SAMPLES = 20


# every completed local root flows through _on_root_close (idempotent
# registration — obs/__init__ imports this module exactly for this)
_add_root_hook(_on_root_close)
