"""Device-cost profiling: compile tracking, dispatch splits, memory.

The serving p50 sat at ~110 ms with `device_rtt_ms` ~100 ms at every
corpus size, and nothing in the telemetry stack could say how much of
that was XLA compilation, host dispatch, transfer, or device compute —
the JobTracker-style counters (PR 3/4) only see the host. This module
is the missing device-cost lens, three instruments in one:

- **Compile observability** — `profiled_jit` is a drop-in `jax.jit`
  replacement used by every compiled entry point (ops/scoring.py,
  ops/postings.py, utils/transfer.py, parallel/sharded_tiered.py). It
  keys every call by its ABSTRACT signature (arg shapes/dtypes + static
  values), detects actual compiles via the jit cache size, records each
  one into the `compile.count` counter and `compile.time` histogram,
  captures `cost_analysis()` FLOPs/bytes per executable (one extra
  lower+compile per new signature; a persistent-compilation-cache hit
  when that is enabled — TPU_IR_PROFILE_COST=0 skips it), and counts a
  `compile.recompiles` event whenever one signature compiles AGAIN — a
  fresh-jit-per-call or cache-thrash bug. More than
  TPU_IR_PROFILE_RECOMPILE_LIMIT compiles of one signature dumps a
  rate-limited `recompile_storm` flight record.
- **Dispatch split** — a `jax.monitoring` duration listener attributes
  jax's own jaxpr-trace and backend-compile events to the profiled call
  in flight, emitted as `dispatch.trace` / `dispatch.compile` sub-spans
  inside the scorer's span tree; the scorer adds `dispatch.device`
  (dispatch → block_until_ready) so the fixed RTT finally decomposes.
- **Memory gauges** — `sample_memory()` reads `device.memory_stats()`
  (bytes_in_use / peak) and the host RSS into the registry's Gauge
  primitive after each dispatch and each H2D stream, so `/metrics`,
  `/profile` and the bench rows carry live + peak memory.

`TPU_IR_PROFILE=0` reduces `profiled_jit.__call__` to one flag test and
the raw jit call. Everything here is import-light (no jax at module
import) so `tpu-ir lint` and the obs package stay JAX-free to load.
"""

from __future__ import annotations

import collections
import threading
import time

from ..utils import envvars
from .registry import get_registry
from .trace import record_span

_ENABLED = envvars.get_bool("TPU_IR_PROFILE")
_COST = envvars.get_bool("TPU_IR_PROFILE_COST")
_STORM_N = envvars.get_int("TPU_IR_PROFILE_RECOMPILE_LIMIT")


def configure(enabled: bool | None = None, cost: bool | None = None,
              recompile_limit: int | None = None) -> None:
    """Runtime overrides of the TPU_IR_PROFILE* env knobs (tests)."""
    global _ENABLED, _COST, _STORM_N
    if enabled is not None:
        _ENABLED = enabled
    if cost is not None:
        _COST = cost
    if recompile_limit is not None:
        _STORM_N = max(1, recompile_limit)


def enabled() -> bool:
    return _ENABLED


# -- the jax.monitoring listener (trace vs backend-compile attribution) -----

# jax records these internally around every compilation; the listener
# folds them into the profiled call currently on this thread, so the
# split costs nothing when no profiled call is in flight.
_EVENT_MAP = {
    "/jax/core/compile/jaxpr_trace_duration": "trace",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "trace",
    "/jax/core/compile/backend_compile_duration": "compile",
}

_tls = threading.local()
_install_lock = threading.Lock()
_listener_installed = False
# True once the duration listener actually registered: compile DETECTION
# then runs on the thread-local event accumulator — a concurrent
# thread's compile fires events on ITS thread, so warm calls racing a
# compiling thread can never be misattributed (the cache-size delta,
# kept as the no-monitoring fallback, is process-global and could)
_listener_active = False


def _listener(name: str, dur_s: float, **kwargs) -> None:
    acc = getattr(_tls, "acc", None)
    key = _EVENT_MAP.get(name)
    if acc is not None and key is not None:
        acc[key] = acc.get(key, 0.0) + dur_s


def _ensure_listener() -> None:
    """Register the duration listener once per process — lazily, from
    ProfiledJit creation, so importing this module never imports jax."""
    global _listener_installed, _listener_active
    if _listener_installed:
        return
    with _install_lock:
        # claim-then-register: the flag flips under the lock so exactly
        # one caller proceeds to the registration OUTSIDE it (jax calls
        # under a lock are a TPU202 hazard); a failed registration
        # stays claimed — the shim falls back to cache-size deltas and
        # wall-time attribution
        if _listener_installed:
            return
        _listener_installed = True
    try:
        from jax import monitoring

        monitoring.register_event_duration_secs_listener(_listener)
        _listener_active = True
    except Exception:  # noqa: BLE001 — older jax: fall back to wall
        pass


# -- the per-function compile ledger ----------------------------------------

_store_lock = threading.Lock()
# label -> {"signatures": {sig_key: stats}, "compiles": n, "recompiles": n}
_functions: dict[str, dict] = {}
# monotonic timestamps of recompile events (the /healthz 60 s window)
_recompile_ts: collections.deque = collections.deque(maxlen=4096)


def _sig_atom(a) -> tuple:
    shape = getattr(a, "shape", None)
    if shape is not None and hasattr(a, "dtype"):
        return ("arr", tuple(shape), str(a.dtype))
    if isinstance(a, (tuple, list)):
        return ("seq", tuple(_sig_atom(x) for x in a))
    return ("static", repr(a))


def signature_key(args: tuple, kwargs: dict) -> tuple:
    """The abstract signature jit keys compilation on, approximated
    host-side: (shape, dtype) per array leaf, repr for static values.
    Hashable; stable across calls with identical abstract inputs."""
    return (tuple(_sig_atom(a) for a in args),
            tuple((k, _sig_atom(kwargs[k])) for k in sorted(kwargs)))


def render_signature(sig: tuple) -> str:
    """Human-readable form of a signature key ('f32[64,8], k=10')."""

    def one(atom) -> str:
        kind = atom[0]
        if kind == "arr":
            return f"{atom[2]}[{','.join(str(d) for d in atom[1])}]"
        if kind == "seq":
            return "(" + ", ".join(one(x) for x in atom[1]) + ")"
        return atom[1]

    args, kwargs = sig
    parts = [one(a) for a in args]
    parts += [f"{k}={one(v)}" for k, v in kwargs]
    return ", ".join(parts)


def _record_compile(label: str, sig: tuple, wall_ns: int, acc: dict,
                    cost: dict | None) -> None:
    reg = get_registry()
    trace_s = acc.get("trace", 0.0)
    compile_s = acc.get("compile", 0.0)
    if trace_s == 0.0 and compile_s == 0.0:
        # no monitoring events (old jax): attribute the whole cold call
        compile_s = wall_ns / 1e9
    if trace_s > 0.0:
        record_span("dispatch.trace", int(trace_s * 1e9), fn=label)
    record_span("dispatch.compile", int(compile_s * 1e9), fn=label)
    reg.incr("compile.count")
    reg.observe("compile.time", trace_s + compile_s)
    with _store_lock:
        fn = _functions.setdefault(
            label, {"signatures": {}, "compiles": 0, "recompiles": 0})
        st = fn["signatures"].setdefault(sig, {
            "compiles": 0, "total_compile_s": 0.0, "last_compile_s": 0.0,
            "trace_s": 0.0, "flops": None, "bytes_accessed": None})
        st["compiles"] += 1
        st["total_compile_s"] = round(
            st["total_compile_s"] + trace_s + compile_s, 6)
        st["last_compile_s"] = round(trace_s + compile_s, 6)
        st["trace_s"] = round(st["trace_s"] + trace_s, 6)
        if cost:
            st.update(cost)
        fn["compiles"] += 1
        recompiled = st["compiles"] > 1
        if recompiled:
            fn["recompiles"] += 1
            _recompile_ts.append(time.monotonic())
        storm = st["compiles"] > _STORM_N
        n_sigs = sum(len(f["signatures"]) for f in _functions.values())
        sig_compiles = st["compiles"]
    reg.set_gauge("compile.signatures", n_sigs)
    if recompiled:
        reg.incr("compile.recompiles")
    if storm:
        # the classic silent perf killer: ONE signature compiling over
        # and over (a fresh jax.jit per call, a thrashing cache). The
        # recorder's per-reason rate limit keeps a storm from flooding
        # the disk with its own evidence.
        from .recorder import flight_dump

        flight_dump("recompile_storm", extra={
            "fn": label,
            "signature": render_signature(sig),
            "compiles": sig_compiles,
            "limit": _STORM_N,
        })


class ProfiledJit:
    """A jitted callable with compile observability. Call it exactly
    like the `jax.jit(fn)` it wraps — execution goes through the real
    jit (identical semantics, donation included); the wrapper only
    watches the jit cache and jax's monitoring events."""

    def __init__(self, fun, label: str, jit_kwargs: dict):
        import jax

        self._jit = jax.jit(fun, **jit_kwargs)
        self.label = label
        self.__wrapped__ = fun
        self.__name__ = label
        self.__doc__ = getattr(fun, "__doc__", None)
        self._seen: set = set()   # signatures called through THIS wrapper
        _ensure_listener()

    def lower(self, *args, **kwargs):
        return self._jit.lower(*args, **kwargs)

    def clear_cache(self) -> None:
        """Drop the underlying jit cache (tests monkeypatching traced
        globals rely on this). The seen-signature set clears with it:
        the next call of any signature recompiles FOR CAUSE and must
        re-probe cost — but it is not a recompile *event* (the cache
        was emptied deliberately, not thrashed), so the ledger entry
        for the wrapped function resets too."""
        self._jit.clear_cache()
        self._seen.clear()
        with _store_lock:
            _functions.pop(self.label, None)

    def _cache_size(self) -> int:
        try:
            return self._jit._cache_size()
        except Exception:  # noqa: BLE001 — jax internals moved: fall back
            return -1      # to first-seen-signature detection

    @staticmethod
    def _abstract(a):
        """The ShapeDtypeStruct twin of one argument: arrays become
        specs (no data — safe even when the real call DONATED the
        buffer), statics pass through, sequences map recursively."""
        shape = getattr(a, "shape", None)
        if shape is not None and hasattr(a, "dtype"):
            import jax

            return jax.ShapeDtypeStruct(tuple(shape), a.dtype)
        if isinstance(a, (tuple, list)):
            return tuple(ProfiledJit._abstract(x) for x in a)
        return a

    def _cost_probe(self, args: tuple, kwargs: dict,
                    ) -> tuple[dict | None, dict]:
        """Per-executable FLOPs / bytes-accessed from XLA's own cost
        model: one AOT lower+compile over ShapeDtypeStruct specs (the
        jaxpr trace is a cache hit right after the real call; the
        backend compile dedupes against the persistent compilation
        cache when enabled). Never lets a probe failure near the
        dispatch. Also returns the probe's own monitoring durations as
        a backfill for stages the real call got from jax caches."""
        prev = getattr(_tls, "acc", None)
        _tls.acc = acc = {}
        try:
            spec_args = tuple(self._abstract(a) for a in args)
            spec_kwargs = {k: self._abstract(v) for k, v in kwargs.items()}
            compiled = self._jit.lower(*spec_args, **spec_kwargs).compile()
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            if not isinstance(ca, dict):
                return None, acc
            return {
                "flops": float(ca.get("flops", -1.0)),
                "bytes_accessed": float(ca.get("bytes accessed", -1.0)),
            }, acc
        except Exception:  # noqa: BLE001 — cost is garnish, never a crash
            return None, acc
        finally:
            _tls.acc = prev

    def __call__(self, *args, **kwargs):
        if not _ENABLED:
            return self._jit(*args, **kwargs)
        # steady-state overhead discipline: the cached-signature path
        # costs one thread-local swap and two timestamps — signature
        # hashing happens ONLY when a compile was detected (measured:
        # hashing ~20 tiered-kernel args per dispatch was the dominant
        # shim cost)
        before = -1 if _listener_active else self._cache_size()
        prev = getattr(_tls, "acc", None)
        _tls.acc = acc = {}
        t0 = time.perf_counter_ns()
        try:
            out = self._jit(*args, **kwargs)
        finally:
            _tls.acc = prev
        wall_ns = time.perf_counter_ns() - t0
        if _listener_active:
            # trace/compile events fired on THIS thread during THIS
            # call = a compile of this signature (tracing always runs
            # for a new signature, even on a persistent-cache hit);
            # immune to concurrent compiles on other threads, which
            # land in their own thread-local accumulators
            compiled = bool(acc)
        else:
            after = self._cache_size()
            if after >= 0 and before >= 0:
                compiled = after > before
            else:
                # no cache introspection either (jax internals moved):
                # fall back to first-seen signatures
                compiled = signature_key(args, kwargs) not in self._seen
        if compiled:
            sig = signature_key(args, kwargs)
            first = sig not in self._seen
            self._seen.add(sig)
            cost = None
            if first and _COST:
                # after the real call on purpose: the probe traces over
                # ShapeDtypeStruct specs (jaxpr cache hit, donation-safe)
                cost, probe_acc = self._cost_probe(args, kwargs)
                for key, v in probe_acc.items():
                    acc.setdefault(key, v)
            _record_compile(self.label, sig, wall_ns, acc, cost)
        return out


def profiled_jit(fun=None, *, label: str | None = None, **jit_kwargs):
    """Drop-in `jax.jit` replacement with compile observability: use as
    `@partial(profiled_jit, static_argnames=(...))` or
    `name = profiled_jit(fn, static_argnames=(...))` — every jit kwarg
    passes straight through. The lint AST index recognizes it as a jit
    wrapper, so TPU101-104 hazard analysis of wrapped bodies and their
    static-argument taint is unchanged."""
    if fun is None:
        return lambda f: profiled_jit(f, label=label, **jit_kwargs)
    return ProfiledJit(fun, label or getattr(fun, "__name__", "<fn>"),
                       jit_kwargs)


# -- memory sampling --------------------------------------------------------


def _host_rss_bytes() -> int:
    """Resident set size of this process, without psutil: /proc on
    linux, ru_maxrss (the peak — close enough for a gauge) elsewhere."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    try:
        import resource
        import sys

        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # ru_maxrss units are platform-defined: KiB on linux/BSD, BYTES
        # on macOS — the one platform that actually reaches this
        # fallback (no /proc); scaling it would inflate the gauge 1024x
        return peak if sys.platform == "darwin" else peak * 1024
    except Exception:  # noqa: BLE001 — exotic platform: no sample
        return 0


def _device_stats() -> dict | None:
    """`memory_stats()` of device 0, or None (CPU backend returns None;
    an UNINITIALIZED backend is never touched — jax.devices() from here
    could otherwise hang a CLI process on the TPU tunnel)."""
    import sys

    if "jax" not in sys.modules:
        return None
    try:
        import jax
        import jax._src.xla_bridge as xb

        if not xb.backends_are_initialized():
            return None
        return jax.devices()[0].memory_stats()
    except Exception:  # noqa: BLE001 — stats are garnish
        return None


_sample_lock = threading.Lock()
_last_sample = 0.0
SAMPLE_MIN_INTERVAL_S = 0.05


def sample_memory(min_interval_s: float | None = None) -> None:
    """One memory sample into the gauges: called by the scorer after
    each device dispatch and by stream_to_device after each upload.
    Rate-limited (default one sample per 50 ms) so a hot single-query
    loop never pays the /proc read per dispatch — the device-side peak
    gauge still cannot miss a spike, because `peak_bytes_in_use` is the
    backend's OWN high-water accumulator, not ours. Pass
    `min_interval_s=0` to force a sample (tests, one-shot snapshots).
    A no-op with profiling disabled."""
    global _last_sample
    if not _ENABLED:
        return
    interval = (SAMPLE_MIN_INTERVAL_S if min_interval_s is None
                else min_interval_s)
    now = time.monotonic()
    with _sample_lock:
        if now - _last_sample < interval:
            return
        _last_sample = now
    reg = get_registry()
    rss = _host_rss_bytes()
    if rss:
        reg.set_gauge("host.rss_bytes", rss)
        reg.update_gauge_max("host.peak_rss_bytes", rss)
    stats = _device_stats()
    if stats:
        in_use = stats.get("bytes_in_use")
        if in_use is not None:
            reg.set_gauge("device.bytes_in_use", in_use)
        peak = stats.get("peak_bytes_in_use", in_use)
        if peak is not None:
            reg.update_gauge_max("device.peak_bytes", peak)


def memory_snapshot() -> dict:
    """Point-in-time memory readout for flight-record headers and the
    /profile report: host RSS plus device memory_stats when a device
    backend is up (None on CPU / uninitialized)."""
    out: dict = {"host_rss_bytes": _host_rss_bytes(), "device": None}
    stats = _device_stats()
    if stats:
        out["device"] = {
            "bytes_in_use": stats.get("bytes_in_use"),
            "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
        }
    return out


# -- the report surfaces ----------------------------------------------------


def recompiles_last_60s(window_s: float = 60.0) -> int:
    """Recompile events in the trailing window — the /healthz field an
    alerting rule can watch for a storm in progress."""
    cutoff = time.monotonic() - window_s
    with _store_lock:
        return sum(1 for ts in _recompile_ts if ts >= cutoff)


def compile_cache_snapshot() -> dict:
    """The compact compile-ledger totals stamped into flight-record
    headers: enough to see a storm in a post-mortem without the full
    per-signature report."""
    cutoff = time.monotonic() - 60.0
    with _store_lock:
        return {
            "functions": len(_functions),
            "signatures": sum(len(f["signatures"])
                              for f in _functions.values()),
            "compiles": sum(f["compiles"] for f in _functions.values()),
            "recompiles": sum(f["recompiles"]
                              for f in _functions.values()),
            "recompiles_last_60s": sum(
                1 for ts in _recompile_ts if ts >= cutoff),
        }


def profile_report() -> dict:
    """THE profiling view (`tpu-ir profile`, GET /profile): per-function
    per-signature compile counts with wall time and cost_analysis
    FLOPs/bytes, the dispatch time split (trace/compile/device) and
    compile.time histograms, the memory gauges, and the recompile
    window. Per-process, like `tpu-ir stats` — meaningful from a
    serving or bench process, empty from a fresh CLI."""
    reg = get_registry()
    snap = reg.snapshot()
    hists = snap.get("histograms", {})
    with _store_lock:
        functions = []
        for label in sorted(_functions):
            fn = _functions[label]
            sigs = [{
                "signature": render_signature(sig),
                **stats,
            } for sig, stats in fn["signatures"].items()]
            sigs.sort(key=lambda s: -s["total_compile_s"])
            functions.append({
                "name": label,
                "compiles": fn["compiles"],
                "recompiles": fn["recompiles"],
                "signatures": sigs,
            })
    dispatch = {
        name: hists[name]
        for name in ("compile.time", "dispatch.trace", "dispatch.compile",
                     "dispatch.device", "dispatch", "kernel")
        if name in hists}
    return {
        "enabled": _ENABLED,
        "functions": functions,
        "compile_counters": {
            "compile.count": snap["counters"].get("compile.count", 0),
            "compile.recompiles": snap["counters"].get(
                "compile.recompiles", 0),
        },
        "recompiles_last_60s": recompiles_last_60s(),
        "dispatch": dispatch,
        # the coalescing scheduler's view (ISSUE 9): batches packed vs
        # solo-flushed, the occupancy distribution (1..top-rung on the
        # bucket scale) and per-slot coalesce wait — read next to the
        # dispatch split above to see what each kernel call amortized
        "batching": {
            "batch.coalesced": snap["counters"].get("batch.coalesced", 0),
            "batch.solo_flush": snap["counters"].get("batch.solo_flush",
                                                     0),
            **{name: hists[name]
               for name in ("batch.occupancy", "batch.wait")
               if name in hists},
        },
        # dynamic pruning (ISSUE 13): the raw scheduling terms behind
        # prune_diag's derived fractions, and the block-max kernels'
        # mask ledger — blocks_masked / blocks_considered is the
        # realized skip fraction, fallback vs saved the engagement rate
        "pruning": {
            name: snap["counters"].get(name, 0)
            for name in ("prune.queries", "prune.queries_hot_free",
                         "prune.blocks_total", "prune.blocks_skip_hot",
                         "blockmax.blocks_considered",
                         "blockmax.blocks_masked",
                         "blockmax.saved_dispatches",
                         "blockmax.fallback_dispatches")
        },
        # the result-cache tier (ISSUE 15): hit/miss/evict/stale
        # counters + derived hit fraction, the lookup-cost histogram,
        # and every live cache's control-plane snapshot — read next to
        # the dispatch split to see what each hit SKIPPED paying
        "cache": _cache_section(snap, hists),
        "gauges": snap.get("gauges", {}),
        "memory": memory_snapshot(),
    }


def _cache_section(snap: dict, hists: dict) -> dict:
    from ..serving.result_cache import cache_counters, live_caches

    out = dict(cache_counters())
    if "cache.lookup" in hists:
        out["cache.lookup"] = hists["cache.lookup"]
    out["caches"] = [c.snapshot() for c in live_caches()]
    return out


def reset_profile() -> None:
    """Forget the compile ledger and recompile window (test isolation —
    wired into obs.reset_all). Wrapper instances keep their own
    seen-signature sets: the underlying jit caches persist too, so a
    signature that stays cached correctly records no new compile."""
    global _last_sample
    with _store_lock:
        _functions.clear()
        _recompile_ts.clear()
    with _sample_lock:
        # the registry reset zeroed the gauges; the next dispatch must
        # re-sample immediately, not wait out the rate limit
        _last_sample = 0.0
