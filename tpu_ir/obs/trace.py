"""Flight-recorder tracing: per-request span trees in a bounded ring.

The reference engine's observability was the Hadoop JobTracker page — a
frozen table of counters per job. Counters say WHAT happened; they never
say where one request spent its time or what the last requests before a
breach looked like. This module is the missing half:

- `trace(name, **attrs)` — a context manager recording one span:
  monotonic start, duration, thread id, free-form attrs, the exception
  (if one escaped), and child spans. Nesting via a thread-local stack
  builds the tree; the serving path's tree is
  request -> (ladder, admission_wait, breaker, dispatch -> kernel*,
  fallback), the build path's is build.<phase> per JobReport phase.
- Every span's duration also lands in the TelemetryRegistry histogram of
  the same name — spans and latency distributions are one instrument.
- Completed ROOT spans go into a process-wide bounded ring buffer
  (`recent_traces()`), the flight recorder's source: on an invariant
  breach the last N request trees are right there, no log scraping.

Overhead discipline: `TPU_IR_TRACE=0` turns `trace()` into a single
flag test returning a shared no-op (pinned near-free by a tight-loop
test); enabled, a span costs two perf_counter_ns calls, one small object
and one locked histogram increment. `TPU_IR_TRACE_SAMPLE=N` keeps every
N-th root trace in the ring (histograms always record — sampling bounds
ring churn, not measurement).

Cross-thread spans: faults.run_with_deadline re-parents its worker
thread onto the caller's current span via `attach()`, so a deadlined
dispatch's kernel spans stay inside the request tree instead of
surfacing as orphan roots.
"""

from __future__ import annotations

import collections
import threading
import time
from contextlib import nullcontext

from ..utils import envvars
from .registry import get_registry

_tls = threading.local()
_ring_lock = threading.Lock()

_ENABLED = envvars.get_bool("TPU_IR_TRACE")
_SAMPLE_N = envvars.get_int("TPU_IR_TRACE_SAMPLE")
_RING = collections.deque(maxlen=envvars.get_int("TPU_IR_TRACE_RING"))
_JAX_ANNOTATE = envvars.get_bool("TPU_IR_JAX_TRACE")
_root_seq = 0
# Root-close hooks: callables fired with every COMPLETED root span,
# unconditionally — BEFORE and independent of the ring's 1-in-N
# sampling, because a subscriber (obs/disttrace.py) applies its own
# keep/drop policy (tail-keeping must see the roots sampling would
# discard). Hooks must never raise and must be cheap: they run inline
# on the request thread at root close.
_root_hooks: list = []


def configure(enabled: bool | None = None, sample: int | None = None,
              ring_capacity: int | None = None,
              jax_annotations: bool | None = None) -> None:
    """Runtime overrides of the TPU_IR_TRACE* env knobs (tests, REPLs)."""
    global _ENABLED, _SAMPLE_N, _RING, _JAX_ANNOTATE
    if enabled is not None:
        _ENABLED = enabled
    if sample is not None:
        _SAMPLE_N = max(1, sample)
    if ring_capacity is not None:
        with _ring_lock:
            _RING = collections.deque(_RING, maxlen=max(1, ring_capacity))
    if jax_annotations is not None:
        _JAX_ANNOTATE = jax_annotations


def enabled() -> bool:
    return _ENABLED


class Span:
    """One timed region; also the context manager that records it."""

    __slots__ = ("name", "attrs", "start_ns", "dur_ns", "thread_id",
                 "thread_name", "wall_time", "children", "error",
                 "_is_root")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self.start_ns = 0
        self.dur_ns = 0
        t = threading.current_thread()
        self.thread_id = t.ident or 0
        self.thread_name = t.name
        self.wall_time = 0.0
        self.children: list[Span] = []
        self.error: str | None = None
        self._is_root = False

    def set(self, key: str, value) -> None:
        """Annotate the span (service level, breaker state, ...)."""
        self.attrs[key] = value

    def __enter__(self) -> "Span":
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        self._is_root = not stack
        if self._is_root:
            self.wall_time = time.time()
        stack.append(self)
        self.start_ns = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.dur_ns = time.perf_counter_ns() - self.start_ns
        if exc is not None:
            self.error = repr(exc)
        stack = getattr(_tls, "stack", None)
        if stack and stack[-1] is self:
            stack.pop()
        parent = stack[-1] if stack else None
        if parent is not None:
            parent.children.append(self)
        get_registry().observe(self.name, self.dur_ns / 1e9)
        if self._is_root:
            _push_root(self)
        return False

    def to_dict(self) -> dict:
        """JSON-ready tree. Copies child/attr containers first: an
        abandoned deadline thread may still be appending to a parent
        that is already being serialized."""
        out = {
            "name": self.name,
            "start_ns": self.start_ns,
            "dur_us": round(self.dur_ns / 1e3, 3),
            "thread_id": self.thread_id,
            "thread": self.thread_name,
        }
        attrs = dict(self.attrs)
        if attrs:
            out["attrs"] = attrs
        if self.error is not None:
            out["error"] = self.error
        if self.wall_time:
            out["time"] = time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.localtime(self.wall_time))
        children = tuple(self.children)
        if children:
            out["children"] = [c.to_dict() for c in children]
        return out


class _NullSpan:
    """The disabled-tracing singleton: enter/exit/set are no-ops."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, key, value):
        pass


_NULL = _NullSpan()


def trace(name: str, **attrs):
    """Open a span (context manager). With tracing disabled this is one
    flag test and a shared no-op object — safe on any hot path."""
    if not _ENABLED:
        return _NULL
    return Span(name, attrs)


def record_span(name: str, dur_ns: int, **attrs) -> None:
    """Record an externally-timed region as a completed child span of
    this thread's current span, plus the histogram observation of the
    same name — the profiling shim's compile/trace sub-spans, whose
    durations come from jax's own monitoring events rather than a
    context manager. Follows trace()'s discipline: with tracing
    disabled this is one flag test and nothing is recorded."""
    if not _ENABLED:
        return
    get_registry().observe(name, dur_ns / 1e9)
    span = Span(name, attrs)
    span.dur_ns = int(dur_ns)
    span.start_ns = time.perf_counter_ns() - span.dur_ns
    stack = getattr(_tls, "stack", None)
    parent = stack[-1] if stack else None
    if parent is not None:
        parent.children.append(span)
    else:
        _push_root(span)


def current_span() -> Span | None:
    """This thread's innermost open span (None outside any trace), the
    handle `attach()` re-parents worker threads onto."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def current_root() -> Span | None:
    """This thread's OUTERMOST open span — the request root the
    slow-query trap (obs/querylog.py) serializes while the request is
    still in flight (its tree won't reach the ring until it closes;
    to_dict() copies child lists, so a mid-flight snapshot is safe)."""
    stack = getattr(_tls, "stack", None)
    return stack[0] if stack else None


class _Attach:
    __slots__ = ("_parent", "_saved")

    def __init__(self, parent):
        self._parent = parent
        self._saved = None

    def __enter__(self):
        self._saved = getattr(_tls, "stack", None)
        _tls.stack = [self._parent] if self._parent is not None else []
        return self

    def __exit__(self, *exc):
        _tls.stack = self._saved if self._saved is not None else []
        return False


def attach(parent: Span | None):
    """Context manager making `parent` (a span from ANOTHER thread) the
    current span on this thread — spans opened inside become its
    children instead of orphan roots. attach(None) just isolates."""
    return _Attach(parent)


def add_root_hook(fn) -> None:
    """Subscribe `fn(span)` to every completed root span (idempotent:
    re-adding the same callable is a no-op). The hook fires before ring
    sampling — subscribers see ALL roots."""
    if fn not in _root_hooks:
        _root_hooks.append(fn)


def remove_root_hook(fn) -> None:
    if fn in _root_hooks:
        _root_hooks.remove(fn)


def _push_root(span: Span) -> None:
    global _root_seq
    for hook in tuple(_root_hooks):
        try:
            hook(span)
        except Exception:  # noqa: BLE001 — a hook bug must not fail the
            pass  # request whose root just closed
    with _ring_lock:
        _root_seq += 1
        if _root_seq % _SAMPLE_N == 0:
            _RING.append(span)


def recent_traces() -> list[Span]:
    """The ring's current contents, oldest first."""
    with _ring_lock:
        return list(_RING)


def clear_traces() -> None:
    with _ring_lock:
        _RING.clear()


def kernel_annotation(name: str):
    """Opt-in jax.profiler named region around a kernel dispatch: with
    TPU_IR_JAX_TRACE=1 (or configure(jax_annotations=True)) the scoring
    dispatches show up as named spans in an xprof/tensorboard capture
    (`--profile DIR`); otherwise a free nullcontext."""
    if not _JAX_ANNOTATE:
        return nullcontext()
    import jax

    return jax.profiler.TraceAnnotation(name)
