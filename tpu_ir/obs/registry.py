"""TelemetryRegistry: the one process-wide home for counters + histograms.

PR 1–2 grew three separate counter surfaces — `recovery_counters()`,
`serving_counters()` (both utils/report.py) and the fault plan's fire
counts — each with its own snapshot/reset story, none with any latency
distribution. This registry unifies them: every process-wide counter
lives here under a dotted namespace (`recovery.*`, `serving.*`,
`fault.*`), every latency histogram lives here under its span/stage name,
and one `snapshot(reset=...)` is the single scrape surface for
`tpu-ir stats` / `tpu-ir metrics` / the flight recorder. The old
functions survive as thin prefix views (utils/report.py), so existing
callers and the `tpu-ir stats` JSON shape keep working.

Declared names: the registry pre-registers a `fault.<site>` counter for
every fault-injection site threaded through the stack and a latency
histogram for every serving stage and service level, so a failure path
or ladder level with NO telemetry is structurally impossible —
tests/test_obs.py introspects the source for injection sites and the
frontend for levels and asserts both land in the declared sets.
"""

from __future__ import annotations

import threading
import uuid

from .histogram import LatencyHistogram, summary_from_counts

# Version of the snapshot dict shape (and of the spooled/allgathered
# serializable form in obs/aggregate.py). Bump on any change a downstream
# parser could trip over; scrapers reject snapshots from a future schema
# instead of mis-parsing them.
SNAPSHOT_SCHEMA = 1

# Every fault-injection site name threaded through the build and serve
# paths (faults.should_fire / maybe_crash / maybe_hang call sites). A new
# site MUST be added here — the registry pre-registers its fire counter,
# and the static-analysis test fails any site found in source but not
# declared (no silently untelemetered failure path).
FAULT_SITES = (
    "spill_write",         # index/format.py: transient spill/part write
    "artifact_truncate",   # index/format.py: torn artifact write
    "crash.builder",       # index/builder.py: death before metadata
    "crash.pass1",         # index/streaming.py: death mid-tokenize
    "crash.pass2",         # index/streaming.py: death mid-postings
    "crash.pass3",         # index/streaming.py: death mid-reduce
    "shuffle_overflow",    # parallel/sharded_build.py: all_to_all drop
    "score.hang",          # search/scorer.py: hung device dispatch
    "score.device_loss",   # search/scorer.py: device lost mid-dispatch
    "tokenize.pool",       # analysis/pool.py: tokenizer pool chunk failure
    # Durable ingest (ISSUE 17) — every declared death point on the
    # write path; the SIGKILL crash-fuzz matrix in tests/test_wal.py
    # kills a child at each one and proves bit-identical recovery.
    "ingest.wal_append",   # index/wal.py: death before a record is framed
    "ingest.wal_torn",     # index/wal.py: death mid-append (torn tail)
    "ingest.wal_retire",   # index/wal.py: death mid WAL-segment retirement
    "ingest.flush_build",  # index/ingest.py: death mid delta-segment build
    "ingest.commit_between",  # index/segments.py: between manifest write
    #                           and the CURRENT pointer rename
    "ingest.merge",        # index/segments.py: death mid-merge, pre-commit
)

# Serving-stage span names (the per-request span tree) — each gets a
# declared latency histogram so `tpu-ir serve-bench` always reports the
# full stage breakdown, observed or not.
REQUEST_STAGES = (
    "admission_wait",  # time from arrival to holding an execution slot
    "ladder",          # service-level decision
    "breaker",         # circuit-breaker consultation
    "dispatch",        # whole device dispatch (deadline window included)
    "kernel",          # one jit'd scoring call (per query block)
    "fallback",        # host-CPU degraded scoring
)

# Service levels the degradation ladder can emit; each gets a
# `request.<level>` end-to-end latency histogram (shed = time-to-shed).
SERVICE_LEVELS = ("full", "no_rerank", "hot_only", "shed")

# Scorer cold/warm-load pipeline stages (ISSUE 5): checksum folding,
# shard reads, CSR assembly, host-to-device streaming. Declared so
# `tpu-ir metrics` and the bench's load breakdown always report the full
# stage set, observed or not; load.h2d pairs with the load.h2d_bytes
# counter for an effective-MB/s readout.
LOAD_STAGES = ("load.verify", "load.read", "load.assemble", "load.h2d")

# Recovery-event counter names (the `recovery.` namespace, incremented
# via utils/report.recovery_counters()). Declared so the lint contract
# pass (TPU303) can reject an increment of an undeclared name — a typo'd
# counter would otherwise silently split its event stream.
RECOVERY_COUNTER_NAMES = (
    "retries", "retry_exhausted", "overflow_retries", "degraded_batches",
    "deadline_expired", "device_loss", "forced_host_batches",
    "integrity_failures", "quarantined", "quarantine_evicted",
    "spill_integrity_discards",
)

# Serving-frontend counter names (the `serving.` namespace; the dynamic
# families served_<level>, shed_<reason>, level_step_<dir> are declared
# as their expansions over SERVICE_LEVELS / shed reasons / directions).
SERVING_COUNTER_NAMES = (
    "submitted", "degraded", "breaker_opened", "breaker_probes",
    "served_breaker_host",
    "served_full", "served_no_rerank", "served_hot_only",
    # result-cache tier (ISSUE 15): requests answered from the
    # frontend's exact-hit cache — no admission slot, no dispatch
    "served_cache",
    "shed_level", "shed_queue_full", "shed_queue_timeout",
    "level_step_down", "level_step_up",
    # live index (ISSUE 12): one frontend published a new generation's
    # scorer (+ coalescer) without dropping in-flight requests
    "generation_swap",
)

# Dispatch sub-stages the device-cost profiler (obs/profiling.py)
# subdivides the scorer's "dispatch" span into (ISSUE 7): tracing +
# lowering, XLA backend compilation, and device execution up to
# block_until_ready — the decomposition of the fixed per-dispatch RTT.
DISPATCH_STAGES = ("dispatch.trace", "dispatch.compile", "dispatch.device")

# Compile-observability counters: every jit compile event through the
# profiling shim, and the subset that re-compiled an already-seen
# abstract signature (the recompile-storm signal).
COMPILE_COUNTER_NAMES = ("compile.count", "compile.recompiles")

# Query-log counters (obs/querylog.py, ISSUE 8): entries recorded into
# the sampled ring, and the subset the slow-query trap force-captured.
QUERYLOG_COUNTER_NAMES = ("querylog.recorded", "querylog.slow")

# Coalescing-scheduler counters (serving/batching.py, ISSUE 9): batches
# that actually packed >1 concurrent query into one padded dispatch, and
# batches flushed with a single occupant (idle arrivals dispatch
# immediately — the solo-latency guarantee).
BATCH_COUNTER_NAMES = ("batch.coalesced", "batch.solo_flush")

# Scatter-gather router counters (serving/router.py, ISSUE 10): the
# one-logical-index-over-N-shard-workers fan-out. requests/served_* and
# shed follow the frontend taxonomy at the ROUTER scope (a routed
# response is exactly one of full/degraded/partial/rejected);
# hedge_fired/hedge_won instrument tail-latency hedging; replica_failed
# and shard_lost count failover events; breaker_opened is the
# per-replica breaker's transition count (the frontend counter of the
# same name is per-process, this one is per-replica-channel).
ROUTER_COUNTER_NAMES = (
    "router.requests", "router.served_full", "router.served_degraded",
    "router.served_partial", "router.shed",
    "router.hedge_fired", "router.hedge_won",
    "router.replica_failed", "router.shard_lost",
    "router.breaker_opened", "router.worker_respawn",
    # live-index rolling upgrades (ISSUE 12): requests whose fan-out saw
    # MORE than one index generation — the router merges only the
    # winning generation's responses and tags the rest missing, so this
    # counts the mixed-generation window's width in requests
    "router.mixed_generation",
)

# Live index subsystem (ISSUE 12): incremental ingest (index/ingest.py),
# tombstone-applying tiered merges (index/segments.py), and the
# zero-downtime generation swap (serving/generation.py). docs_* count
# API-level mutations; flushes/segments_built the delta-segment commits;
# merge.runs one policy-driven compaction step, merge.segments_merged
# its inputs, merge.docs_dropped tombstones physically applied;
# generation.commits every manifest+CURRENT flip.
INGEST_COUNTER_NAMES = (
    "ingest.docs_added", "ingest.docs_updated", "ingest.docs_deleted",
    "ingest.flushes", "ingest.segments_built",
    "merge.runs", "merge.segments_merged", "merge.docs_dropped",
    "generation.commits",
    # Durable ingest (ISSUE 17, index/wal.py): wal_appends one per
    # acknowledged mutation framed into the log, wal_fsyncs the batched
    # durability barriers actually paid (appends/fsyncs is the batching
    # ratio), wal_torn_tail_truncated the mid-append death scars
    # truncated loudly on reopen, wal_segments_retired log segments
    # deleted once a committed watermark fully covered them;
    # replayed the records re-applied past the manifest watermark on
    # writer open (nonzero == a crash was recovered); lease_acquired /
    # lease_takeovers / lease_conflicts the single-writer lease verdicts
    # (takeover = stale-or-dead holder displaced, conflict = a live
    # second writer refused with WriterLeaseHeld).
    "ingest.wal_appends", "ingest.wal_fsyncs",
    "ingest.wal_torn_tail_truncated", "ingest.wal_segments_retired",
    "ingest.replayed",
    "ingest.lease_acquired", "ingest.lease_takeovers",
    "ingest.lease_conflicts",
)

# Radix-partitioned streaming build (ISSUE 11): pass-1 bucketed pair
# spills and the pass-2 per-bucket device reduces. bucket_spills counts
# spill files written, spill_bytes their on-disk size (the per-phase
# bytes the scaling sweep records), tokenize.pool_chunks the chunks the
# multiprocess tokenizer analyzed out-of-process, and pipeline_stalls
# the times the device had to WAIT on the host prefetch (a high count
# says raise TPU_IR_PIPE_DEPTH or bucket count).
BUILD_COUNTER_NAMES = (
    "build.radix.bucket_spills", "build.radix.spill_bytes",
    "build.radix.pipeline_stalls", "build.tokenize.pool_chunks",
)

# Dynamic pruning (ISSUE 13). prune.*: the raw terms behind the derived
# fractions Scorer.prune_diag reports — queries scheduled, the hot-free
# subset (hot-stage upper bound exactly 0, dispatched through the static
# cold-only kernel), and dispatch blocks total / cold-only.
# blockmax.*: the block-max kernels' mask decisions — doc-block lanes
# considered and masked (the skip fraction's raw terms), dispatches
# whose bounds let the pruned hot stage run (saved), and dispatches
# whose surviving blocks overflowed the candidate budget and fell back
# to the exact full-width stage in-kernel.
PRUNE_COUNTER_NAMES = (
    "prune.queries", "prune.queries_hot_free",
    "prune.blocks_total", "prune.blocks_skip_hot",
    "blockmax.blocks_considered", "blockmax.blocks_masked",
    "blockmax.saved_dispatches", "blockmax.fallback_dispatches",
)

# Generation-keyed exact-hit result cache (ISSUE 15,
# serving/result_cache.py): hit/miss the lookup verdicts (hit_fraction =
# hit / (hit + miss)), evict the LRU displacements under the bounded
# capacity, stale_generation the entries invalidated because the serving
# generation moved past them (unreachable by key the moment the
# generation bumped — the count is accounting for the purge, never the
# invalidation mechanism).
CACHE_COUNTER_NAMES = (
    "cache.hit", "cache.miss", "cache.evict", "cache.stale_generation",
)

# Elastic ShardSet membership protocol (ISSUE 16, serving/autoscale.py +
# shardset.py): scale.up / scale.down count replicas that ENTERED /
# LEFT the dispatch grid (one per (shard, replica) membership change,
# so a whole-fleet grow on S shards counts S); scale.drain_inflight the
# peak in-flight requests a draining replica was observed finishing
# (drain-not-drop accounting: these requests completed, none dropped);
# scale.cooldown_skipped decisions the autoscaler WANTED to take but
# suppressed inside the cooldown window — the flap-damper's readout.
SCALE_COUNTER_NAMES = (
    "scale.up", "scale.down", "scale.drain_inflight",
    "scale.cooldown_skipped",
)

# Distributed request tracing + SLO layer (ISSUE 18, obs/disttrace.py).
# disttrace.minted counts contexts born here (router admission or an
# unrouted frontend search); adopted the contexts read off an incoming
# traceparent header (worker side — an adopted trace always exports, the
# sampling verdict belongs to the minting process); spans_exported span
# records shipped off-process (RPC piggyback or spool); spans_dropped
# records discarded because a store ring was full; kept_tail roots kept
# by the tail rule (slow/partial/degraded/hedged/error), kept_sampled
# roots kept by the 1-in-N dice, dropped_sampled roots the dice
# discarded; stitched whole-trace assemblies served (live /trace/<id> or
# post-mortem from spools). slo.good / slo.bad classify every finished
# request against TPU_IR_SLO_P99_MS + the availability target;
# slo.burn_breach counts multi-window budget-burn trips (each one also
# flight-records).
DISTTRACE_COUNTER_NAMES = (
    "disttrace.minted", "disttrace.adopted",
    "disttrace.spans_exported", "disttrace.spans_dropped",
    "disttrace.kept_tail", "disttrace.kept_sampled",
    "disttrace.dropped_sampled", "disttrace.stitched",
    "slo.good", "slo.bad", "slo.burn_breach",
)

# Telemetry time machine (ISSUE 19, obs/timeseries.py).
# timeseries.samples counts base-rate windows taken off the registry,
# timeseries.rollups exact fine->coarse tier merges, and
# timeseries.anomaly MAD z-score detections on the curated series
# (each detection also writes a rate-limited "anomaly" flight record).
# forecast.fits counts sinusoid fits that PASSED the quality gate and
# published the forecast_occupancy gauge; forecast.scaleups the
# autoscaler scale-ups whose deciding signal was the forecast (reason
# "forecast" — growth started before the burst, not after the queue).
TIMESERIES_COUNTER_NAMES = (
    "timeseries.samples", "timeseries.rollups", "timeseries.anomaly",
    "forecast.fits", "forecast.scaleups",
)

# Compressed quantized arena (ISSUE 20, index/compress.py).
# compress.shards / compress.bytes_in / compress.bytes_out account each
# shard encode at migrate/build-hook time (the ratio doctor reports is
# recomputed from disk, not from these). decode.blocks_decoded /
# blocks_skipped count posting groups a shard decode unpacked vs skipped
# (doc-range workers: skipped grows with what the range excludes);
# decode.bytes / bytes_skipped the payload bytes behind each — the
# memory-lean pin reads bytes_skipped directly.
COMPRESS_COUNTER_NAMES = (
    "compress.shards", "compress.bytes_in", "compress.bytes_out",
    "decode.blocks_decoded", "decode.blocks_skipped",
    "decode.bytes", "decode.bytes_skipped",
)

DECLARED_COUNTERS = tuple(f"fault.{s}" for s in FAULT_SITES) + (
    # bytes streamed host-to-device across all uploads (pairs with the
    # load.h2d histogram for an effective-MB/s readout)
    "load.h2d_bytes",
) + (COMPILE_COUNTER_NAMES + QUERYLOG_COUNTER_NAMES + BATCH_COUNTER_NAMES
     + ROUTER_COUNTER_NAMES + BUILD_COUNTER_NAMES + INGEST_COUNTER_NAMES
     + PRUNE_COUNTER_NAMES + CACHE_COUNTER_NAMES + SCALE_COUNTER_NAMES
     + DISTTRACE_COUNTER_NAMES + TIMESERIES_COUNTER_NAMES
     + COMPRESS_COUNTER_NAMES)
# "request" (the root span, all levels pooled) rides alongside the
# per-level request.<level> histograms — same observations, two cuts
DECLARED_HISTOGRAMS = ("request",) + REQUEST_STAGES + LOAD_STAGES + tuple(
    f"request.{lv}" for lv in SERVICE_LEVELS) + DISPATCH_STAGES + (
    # wall time per compile event (trace + backend compile)
    "compile.time",
    # one score-explain computation (search/explain.py — the (L+1)-row
    # prefix dispatch plus metadata assembly)
    "explain",
    # one slow-query force-capture (span tree + explain + flight dump)
    "querylog.slow_capture",
    # one compressed shard decode (ISSUE 20): unpack + canonical-order
    # restore wall seconds — deliberately OUTSIDE the load.read span so
    # load_read_s keeps measuring bytes-off-disk and drops with them
    "decode.block",
    # coalescing scheduler (ISSUE 9): batch occupancy per dispatched
    # batch (a COUNT observed on the latency bucket scale — 1..64 lands
    # exactly; p50 occupancy > 1 is the "coalescing engaged" proof) and
    # per-slot queue wait (enqueue -> dispatch start, seconds)
    "batch.occupancy",
    "batch.wait",
    # scatter-gather router (ISSUE 10): end-to-end routed request
    # latency, per-shard worker round trips (hedges observe too — each
    # completed replica call is one RTT sample), and the host-side
    # exact top-k merge cost
    "router.request",
    "router.shard_rtt",
    "router.merge",
    # radix streaming build (ISSUE 11): valid pairs each pass-2 bucket
    # reduce produced (bucket-balance readout — a skewed distribution
    # shows up as a wide histogram) and the wall seconds one bucket's
    # read->remap->reduce->spill round took
    "build.radix.bucket_pairs",
    "build.radix.bucket_s",
    # live index (ISSUE 12): one buffer->delta-segment flush (build +
    # commit), one tombstone-applying tiered merge step, and one serving
    # generation swap (new-generation load + precompile + publish — the
    # requests-keep-flowing wall, not a downtime window)
    "ingest.flush",
    "merge.run",
    "generation.swap",
    # durable ingest (ISSUE 17): wall seconds one WAL replay took on
    # writer open (records past the watermark re-applied to the buffer),
    # and the ingest+serve soak's freshness lag — flush commit to the
    # FIRST query answered from a servable generation containing it
    # (seconds on the wire, reported in ms like every histogram)
    "ingest.replay",
    "ingest.freshness",
    # result-cache tier (ISSUE 15): one cache lookup (key build + LRU
    # probe) — the cost a hit pays INSTEAD of the fan-out/dispatch, so
    # p50 here vs router.request/request.full is the cache's win
    "cache.lookup",
    # elastic membership (ISSUE 16): wall seconds one drain took
    # (draining-state entry -> process exit; the summary reports it in
    # ms like every histogram) and wall seconds one scale-up's spawn +
    # precompile/residency warm-up took before the replica entered the
    # dispatch grid — the warm-start gate's cost, paid OUTSIDE traffic
    "scale.drain_ms",
    "scale.warmup_ms",
    # distributed tracing (ISSUE 18): wall seconds one whole-trace
    # stitch took (live ingest_remote merge or post-mortem spool walk)
    "disttrace.stitch",
    # durable-ingest spans (ISSUE 18 satellite over ISSUE 17): every
    # span name observed outside obs/ must be declared — one WAL record
    # framed+written, one batched fsync barrier actually paid, one
    # replay pass on writer open, and the segment-build half of a flush
    # (ingest.flush above times the whole flush including commit)
    "ingest.wal_append",
    "ingest.wal_fsync",
    "ingest.wal_replay",
    "ingest.flush_build",
)

# Gauges: point-in-time values (memory levels, cache sizes) — unlike
# counters they neither accumulate nor reset-to-interval; the merge
# policy says how N process snapshots fold into one cluster value:
# "last" = the newest snapshot's value wins (current level), "max" =
# the cluster-wide peak survives (high-water marks). obs/aggregate.py
# reads this map; an undeclared gauge merges "last".
GAUGE_MERGE = {
    "device.bytes_in_use": "last",   # device HBM currently allocated
    "device.peak_bytes": "max",      # high-water HBM across the run
    "host.rss_bytes": "last",        # process resident set size
    "host.peak_rss_bytes": "max",    # high-water RSS across the run
    "compile.signatures": "last",    # distinct (fn, signature) pairs seen
    # live index (ISSUE 12): the generation a process last committed or
    # swapped to, and that generation's segment/tombstone topology —
    # "last" merges: the levels are per-process currents, not peaks
    "generation.current": "last",
    "generation.segments": "last",
    "generation.tombstones": "last",
    # durable ingest (ISSUE 18 satellite): flush-commit -> first
    # servable-query freshness lag, surfaced live in /healthz (the
    # ingest.freshness histogram keeps the distribution; this gauge is
    # the current level a scrape reads without a soak)
    "ingest.freshness_lag_ms": "last",
    # SLO burn-rate tracker (ISSUE 18, obs/disttrace.py): current
    # multi-window budget-burn multiples — 1.0 burns the budget exactly
    # at the allowed rate; the breach rule requires BOTH windows over
    # threshold so a single spike can't page
    "slo.burn_fast": "last",
    "slo.burn_slow": "last",
    # telemetry time machine (ISSUE 19): the admission occupancy the
    # autoscaler computed on its last tick (the raw series the diurnal
    # fit reads), and the fit's output — predicted occupancy
    # TPU_IR_SCALE_LEAD_S in the future, the third scale-up signal.
    # Both are per-process currents, so "last" merges.
    "router.occupancy": "last",
    "forecast_occupancy": "last",
}
DECLARED_GAUGES = tuple(sorted(GAUGE_MERGE))


def _prom_name(name: str) -> str:
    return name.replace(".", "_").replace("-", "_")


class TelemetryRegistry:
    """Process-wide counters + latency histograms, one snapshot/reset
    API. All methods are thread-safe; the hot-path cost of an increment
    or observation is one dict lookup plus one locked add (the existing
    counter lock discipline — no new locking model)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, int] = {n: 0 for n in DECLARED_COUNTERS}
        self._gauges: dict[str, float] = {n: 0.0 for n in DECLARED_GAUGES}
        # gauges a caller actually SET this interval: the local snapshot
        # reports every declared gauge (presence contract), but only set
        # ones cross process boundaries — a process that never sampled
        # memory must not last-wins-zero the cluster's real levels
        self._gauges_set: set[str] = set()
        self._hists: dict[str, LatencyHistogram] = {
            n: LatencyHistogram() for n in DECLARED_HISTOGRAMS}
        # seq: strictly monotonic per scrape/reset, NEVER zeroed — two
        # snapshots with the same run_id order by seq, so a concurrent
        # scraper can tell "newer scrape" from "state was reset" without
        # heuristics on counter values. resets counts every zeroing event
        # (snapshot(reset=True) and reset()): a scraper seeing it change
        # between two of its own scrapes knows a third party drained the
        # interval it thought it owned. run_id identifies this process
        # lifetime (spool dedup across restarts/pid reuse).
        self._seq = 0
        self._resets = 0
        self.run_id = uuid.uuid4().hex

    @property
    def seq(self) -> int:
        """The last-issued scrape/reset sequence number — a read, NOT a
        scrape: it neither bumps seq nor copies any state (liveness
        probes poll this; a full snapshot per /healthz would be waste)."""
        with self._lock:
            return self._seq

    # -- counters ----------------------------------------------------------

    def incr(self, name: str, amount: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + amount

    def get(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def counter_names(self) -> tuple:
        with self._lock:
            return tuple(self._counters)

    def counters(self, prefix: str = "") -> dict[str, int]:
        """Counter snapshot; with a prefix, only matching counters, the
        prefix stripped (the RecoveryCounters-alias view)."""
        with self._lock:
            if not prefix:
                return dict(self._counters)
            n = len(prefix)
            return {k[n:]: v for k, v in self._counters.items()
                    if k.startswith(prefix)}

    def reset_counters(self, prefix: str = "") -> None:
        """Zero counters under `prefix` ('' = all). Declared counters are
        kept at 0 (presence is the contract), undeclared ones dropped.
        A zeroing event like any other: bumps seq/resets in the same
        lock hold, so scrapers detect even partial (prefix) drains."""
        with self._lock:
            for k in list(self._counters):
                if k.startswith(prefix):
                    if k in DECLARED_COUNTERS:
                        self._counters[k] = 0
                    else:
                        del self._counters[k]
            self._seq += 1
            self._resets += 1

    # -- gauges ------------------------------------------------------------

    def set_gauge(self, name: str, value: float) -> None:
        """Set a point-in-time level (bytes in use, RSS, cache size)."""
        with self._lock:
            self._gauges[name] = float(value)
            self._gauges_set.add(name)

    def update_gauge_max(self, name: str, value: float) -> None:
        """Raise a high-water-mark gauge to `value` if it is higher —
        the peak-memory idiom (a level sample must never WALK a peak
        back down)."""
        with self._lock:
            self._gauges_set.add(name)
            if float(value) > self._gauges.get(name, 0.0):
                self._gauges[name] = float(value)

    def get_gauge(self, name: str) -> float:
        with self._lock:
            return self._gauges.get(name, 0.0)

    def gauges(self) -> dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    # -- histograms --------------------------------------------------------

    def histogram(self, name: str) -> LatencyHistogram:
        h = self._hists.get(name)
        if h is None:
            with self._lock:
                h = self._hists.setdefault(name, LatencyHistogram())
        return h

    def observe(self, name: str, seconds: float) -> None:
        self.histogram(name).observe(seconds)

    def histogram_names(self) -> tuple:
        with self._lock:
            return tuple(self._hists)

    def hist_state(self) -> dict[str, tuple[list[int], float]]:
        """{name: (bucket counts, total seconds)} — the before-image for
        delta summaries (serve-bench reports per-run percentiles without
        resetting process-wide state)."""
        with self._lock:
            hists = dict(self._hists)
        return {n: h.state() for n, h in hists.items()}

    def delta_summary(self, before: dict, always: tuple = ()) -> dict:
        """Per-histogram summaries of observations made SINCE `before`
        (a hist_state() snapshot). Names in `always` are reported even
        with zero new observations — the serve-bench stage contract."""
        out = {}
        for name, (counts, sum_s) in self.hist_state().items():
            b_counts, b_sum = before.get(name, ([0] * len(counts), 0.0))
            d = [a - b for a, b in zip(counts, b_counts)]
            if sum(d) > 0 or name in always:
                out[name] = summary_from_counts(d, sum_s - b_sum)
        return out

    # -- the scrape surface ------------------------------------------------

    def _collect(self, reset: bool):
        """One read of everything — counters under a single lock hold
        (read-and-zero when resetting), histograms via state()/drain().
        The shared core of snapshot() and prometheus_text(): every
        scrape surface gets the same atomicity, so with reset=True a
        concurrent increment or observation lands in exactly one
        interval, never in none. Returns (counters, hist states, meta):
        meta carries the schema version, this scrape's seq, the reset
        count and the process run_id — assigned under the same lock
        hold as the counter read, so seq order IS counter-state order."""
        with self._lock:
            self._seq += 1
            if reset:
                self._resets += 1
            meta = {"schema": SNAPSHOT_SCHEMA, "seq": self._seq,
                    "resets": self._resets, "run_id": self.run_id}
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            gauges_set = set(self._gauges_set)
            if reset:
                for k in list(self._counters):
                    if k in DECLARED_COUNTERS:
                        self._counters[k] = 0
                    else:
                        del self._counters[k]
                # gauges reset with everything else: declared levels
                # return to 0 (presence is the contract, and the next
                # sample restores the live level), undeclared ones drop
                for k in list(self._gauges):
                    if k in DECLARED_GAUGES:
                        self._gauges[k] = 0.0
                    else:
                        del self._gauges[k]
                self._gauges_set.clear()
            hists = dict(self._hists)
        states = {n: (h.drain() if reset else h.state())
                  for n, h in hists.items()}
        return counters, gauges, gauges_set, states, meta

    def collect_state(self, reset: bool = False) -> dict:
        """The SERIALIZABLE raw snapshot: counters plus raw histogram
        bucket counts (not percentile summaries), stamped with schema /
        seq / resets / run_id. This is the cross-process exchange unit —
        obs/aggregate.py spools it, allgathers it, and merges N of them
        bucket-wise; summaries don't merge, bucket counts do. Gauges
        here carry only the names a caller actually SET: an idle
        process's declared-at-0.0 defaults must not last-wins-zero the
        cluster's real levels in the merge."""
        counters, gauges, gauges_set, states, meta = self._collect(reset)
        return {**meta,
                "counters": counters,
                "gauges": {k: v for k, v in gauges.items()
                           if k in gauges_set},
                "histograms": {n: {"counts": list(c), "sum_s": s}
                               for n, (c, s) in states.items()}}

    def snapshot(self, reset: bool = False) -> dict:
        """Everything, one dict: {"schema": ..., "seq": ..., "resets":
        ..., "counters": {...}, "histograms": {name: summary}}.
        `reset=True` is the per-interval scrape — the explicit
        between-runs reset `tpu-ir stats`/serve-bench lacked (see
        _collect for the no-lost-update guarantee)."""
        counters, gauges, _set, states, meta = self._collect(reset)
        return {**meta,
                "counters": counters,
                "gauges": gauges,
                "histograms": {n: summary_from_counts(c, s)
                               for n, (c, s) in states.items()}}

    def reset(self) -> None:
        with self._lock:
            # counter zeroing and the seq/resets bump in ONE lock hold:
            # a concurrent scrape must never observe drained counters
            # with an unchanged resets stamp (that window is exactly the
            # undetected third-party reset `resets` exists to expose)
            for k in list(self._counters):
                if k in DECLARED_COUNTERS:
                    self._counters[k] = 0
                else:
                    del self._counters[k]
            for k in list(self._gauges):
                if k in DECLARED_GAUGES:
                    self._gauges[k] = 0.0
                else:
                    del self._gauges[k]
            self._gauges_set.clear()
            self._seq += 1
            self._resets += 1
            hists = dict(self._hists)
        # histograms are zeroed IN PLACE and never deleted: histogram()
        # hands out long-lived references (span exits hold them), and an
        # observe racing a reset must land in the live object — counted
        # in the next interval — not in a dropped orphan
        for h in hists.values():
            h.reset()

    def prometheus_text(self, reset: bool = False) -> str:
        """Prometheus text exposition: counters as one labeled family,
        histograms in the native cumulative-bucket format. Every family
        carries its `# HELP`/`# TYPE` metadata pair (HELP first, the
        order scrapers expect) so nothing is left to inference.
        `reset=True` drains atomically, same as snapshot(reset=True)."""
        from .histogram import BOUNDS

        counters, gauges, _set, states, _ = self._collect(reset)
        lines = [
            "# HELP tpu_ir_events_total Monotonic event counters; one "
            "series per declared dotted name (label \"name\"), zeroed "
            "only by an explicit reset.",
            "# TYPE tpu_ir_events_total counter",
        ]
        for name, v in sorted(counters.items()):
            lines.append(f'tpu_ir_events_total{{name="{name}"}} {v}')
        lines.append(
            "# HELP tpu_ir_gauge Point-in-time levels; one series per "
            "declared dotted name (label \"name\"), merge policy per "
            "GAUGE_MERGE.")
        lines.append("# TYPE tpu_ir_gauge gauge")
        for name, v in sorted(gauges.items()):
            lines.append(f'tpu_ir_gauge{{name="{name}"}} {v!r}')
        lines.append(
            "# HELP tpu_ir_stage_latency_seconds Stage wall time on "
            "fixed log2 buckets; one series set per declared histogram "
            "(label \"stage\"), cumulative le buckets.")
        lines.append("# TYPE tpu_ir_stage_latency_seconds histogram")
        for name in sorted(states):
            counts, sum_s = states[name]
            stage = _prom_name(name)
            cum = 0
            for i, c in enumerate(counts):
                cum += c
                le = repr(BOUNDS[i]) if i < len(BOUNDS) else "+Inf"
                lines.append(
                    f'tpu_ir_stage_latency_seconds_bucket'
                    f'{{stage="{stage}",le="{le}"}} {cum}')
            lines.append(
                f'tpu_ir_stage_latency_seconds_sum{{stage="{stage}"}} '
                f'{sum_s!r}')
            lines.append(
                f'tpu_ir_stage_latency_seconds_count{{stage="{stage}"}} '
                f'{cum}')
        return "\n".join(lines) + "\n"


_REGISTRY = TelemetryRegistry()


def get_registry() -> TelemetryRegistry:
    """The process-wide TelemetryRegistry singleton."""
    return _REGISTRY
