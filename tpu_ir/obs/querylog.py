"""Sampled query log + slow-query trap: per-request forensics in a ring.

The PR 3/4/7 telemetry explains *where time goes* (spans, histograms,
compile splits); nothing so far records *which queries* a process served
or freezes the full picture of the ones that were slow. This module is
that per-request ledger:

- **The ring**: every query answered by the Scorer lands one bounded
  entry — analyzed terms (or a stable hash when redacted), service
  level, per-stage latency split, batch id (the link a padded shared
  batch needs for per-request attribution — ROADMAP 3), top-k docids +
  scores, and the MaxScore prune/skip decision. `TPU_IR_QUERYLOG_RING`
  bounds it; `TPU_IR_QUERYLOG_SAMPLE=N` keeps every N-th entry (slow
  queries always record, sampling bounds ring churn, not the trap).

- **Redaction**: with `TPU_IR_QUERYLOG_REDACT=1` entries carry only
  `query_hash` (a stable CRC over the analyzed terms) — enough to
  correlate repeats and join against a flight record, nothing a human
  can read back. The hash is always present either way.

- **The slow-query trap**: a request slower than `TPU_IR_SLOW_QUERY_MS`
  is force-captured: its full span tree (read from the still-open trace
  stack), a score explain for its top hit (computed by the caller-
  supplied callable ONLY when the flight recorder's per-reason rate
  limit admits a dump — a storm of slow queries must not double the
  load with explain dispatches), the entry itself, and a
  `slow_query` flight-recorder artifact. The last-K captures
  (`TPU_IR_QUERYLOG_SLOW_KEEP`) stay readable via `tpu-ir querylog`,
  `/querylog`, and ride compactly in every flight-record header.

Counters (`querylog.recorded`, `querylog.slow`) and the
`querylog.slow_capture` histogram are declared in the registry
(obs/registry.py) so the lint TPU303/TPU305 contracts cover them.
Steady-state overhead is pinned <= 5% on the serve soak
(tests/test_querylog.py), mirroring PR 3's <= 10% tracing pin.
"""

from __future__ import annotations

import collections
import threading
import time
import zlib

from ..utils import envvars
from .trace import current_root, recent_traces
from .recorder import flight_dump
from .registry import get_registry

_lock = threading.Lock()
_tls = threading.local()

_ENABLED = envvars.get_bool("TPU_IR_QUERYLOG")
_SAMPLE_N = envvars.get_int("TPU_IR_QUERYLOG_SAMPLE")
_REDACT = envvars.get_bool("TPU_IR_QUERYLOG_REDACT")
_SLOW_MS = envvars.get_float("TPU_IR_SLOW_QUERY_MS")
_RING = collections.deque(maxlen=envvars.get_int("TPU_IR_QUERYLOG_RING"))
_SLOW = collections.deque(
    maxlen=envvars.get_int("TPU_IR_QUERYLOG_SLOW_KEEP"))
_seq = 0
_batch_seq = 0
_slow_times: collections.deque = collections.deque(maxlen=1024)
# compact, IMMUTABLE per-offender stamps for flight-record headers —
# appended before the dump (so the artifact reporting a slow query
# lists that query) while the full capture publishes to _SLOW only
# once fully formed (readers json.dumps these concurrently; a dict
# mutated after publication races serialization)
_slow_headers: collections.deque = collections.deque(maxlen=64)

_HEADER_KEYS = ("query_hash", "level", "total_ms", "analyze_ms",
                "dispatch_ms", "time", "batch_id",
                # coalesced-serving attribution (ISSUE 9): how long the
                # slow offender waited to coalesce and how full its
                # shared batch was — the first two questions a slow
                # query inside a batch raises
                "queue_wait_ms", "batch_occupancy",
                # the distributed-trace join key (ISSUE 18): a slow
                # offender's flight header points at the ONE stitched
                # waterfall that explains it (`tpu-ir trace <id>`)
                "trace_id")


def configure(enabled: bool | None = None, sample: int | None = None,
              ring_capacity: int | None = None,
              redact: bool | None = None, slow_ms: float | None = None,
              slow_keep: int | None = None) -> None:
    """Runtime overrides of the TPU_IR_QUERYLOG* env knobs (tests,
    REPLs) — the obs.trace.configure idiom."""
    global _ENABLED, _SAMPLE_N, _REDACT, _SLOW_MS, _RING, _SLOW
    if enabled is not None:
        _ENABLED = enabled
    if sample is not None:
        _SAMPLE_N = max(1, sample)
    if redact is not None:
        _REDACT = redact
    if slow_ms is not None:
        _SLOW_MS = max(0.0, slow_ms)
    with _lock:
        if ring_capacity is not None:
            _RING = collections.deque(_RING, maxlen=max(1, ring_capacity))
        if slow_keep is not None:
            _SLOW = collections.deque(_SLOW, maxlen=max(1, slow_keep))


def enabled() -> bool:
    return _ENABLED


def slow_query_ms() -> float:
    """The slow-query threshold in ms; 0 disables the trap."""
    return _SLOW_MS


def redacted() -> bool:
    return _REDACT


def next_batch_id() -> int:
    """Process-monotonic id stamped on every entry of one search_batch
    dispatch — the join key for per-request attribution inside a shared
    (padded) batch."""
    global _batch_seq
    with _lock:
        _batch_seq += 1
        return _batch_seq


def query_hash(terms) -> str:
    """Stable hex digest over the analyzed term sequence — the
    redaction-safe identity entries and flight headers correlate on."""
    blob = "\x1f".join(str(t) for t in terms).encode("utf-8")
    return f"{zlib.crc32(blob) & 0xFFFFFFFF:08x}"


class request_context:
    """Thread-local annotations for entries recorded under this context
    — the ServingFrontend wraps the scorer call so entries carry the
    ladder's true service level (the scorer alone only knows flags)."""

    __slots__ = ("_fields", "_saved")

    def __init__(self, **fields):
        self._fields = fields
        self._saved = None

    def __enter__(self):
        self._saved = getattr(_tls, "fields", None)
        _tls.fields = self._fields
        return self

    def __exit__(self, *exc):
        _tls.fields = self._saved
        return False


def context_fields() -> dict:
    return getattr(_tls, "fields", None) or {}


def record(entry: dict, explain_fn=None) -> dict:
    """Record one per-query entry; returns it (seq/ts/slow stamped).

    Sampling keeps every N-th entry in the ring; a SLOW entry (total_ms
    at/above the trap threshold) always records, grows a full capture
    (span tree + explain when the rate limit admits the dump) in the
    slow ring, and writes a `slow_query` flight artifact. `explain_fn`
    is called only inside an admitted dump — never on the sampled-out
    or rate-limited path."""
    if not _ENABLED:
        return entry
    global _seq
    reg = get_registry()
    entry.setdefault("time",
                     time.strftime("%Y-%m-%dT%H:%M:%S"))
    entry.update(context_fields())
    if "trace_id" not in entry or entry["trace_id"] is None:
        # the coalescer stamps a follower's id via slot_meta (the entry
        # is recorded on the LEADER's thread); everyone else gets the
        # thread-local context — None stays None (tracing off)
        from . import disttrace

        tid = disttrace.current_trace_id()
        if tid is not None:
            entry["trace_id"] = tid
        else:
            entry.pop("trace_id", None)
    slow = (_SLOW_MS > 0.0
            and float(entry.get("total_ms", 0.0)) >= _SLOW_MS)
    if slow:
        # stamped BEFORE the ring append: a published entry is never
        # mutated again (concurrent scrapes json.dumps live references)
        entry["slow"] = True
    with _lock:
        _seq += 1
        entry["seq"] = _seq
        keep = slow or (_seq % _SAMPLE_N == 0)
        if keep:
            _RING.append(entry)
    if keep:
        # the counter counts KEPT entries — what a scraper can actually
        # read back, not sampled-out ghosts
        reg.incr("querylog.recorded")
    if slow:
        reg.incr("querylog.slow")
        with _lock:
            _slow_times.append(time.monotonic())
        _capture_slow(entry, explain_fn)
    return entry


def _capture_slow(entry: dict, explain_fn) -> None:
    """Force-capture a slow offender: span tree + (rate-limited) explain
    + flight record. Never raises — the trap runs on the serving path."""
    t0 = time.perf_counter()
    capture = dict(entry)
    try:
        root = current_root()
        if root is not None:
            # the still-open request tree (the ServingFrontend path:
            # its "request" root is live on this thread)
            capture["span_tree"] = root.to_dict()
        else:
            # plain Scorer calls record after their dispatch root spans
            # closed into the ring — take the newest (best-effort: under
            # concurrency it can belong to a neighboring request)
            recent = recent_traces()
            if recent:
                capture["span_tree"] = recent[-1].to_dict()
                capture["span_tree_source"] = "ring"
    except Exception:  # noqa: BLE001 — the trap must not fail a request
        pass
    # the compact header stamp publishes BEFORE the dump (so the flight
    # artifact reporting this query lists it) and is never mutated; the
    # full capture publishes to _SLOW only once fully formed below —
    # concurrent scrapes serialize published dicts, so nothing published
    # may change size afterwards
    with _lock:
        _slow_headers.append({k: capture[k] for k in _HEADER_KEYS
                              if k in capture})

    def extra():
        # evaluated by flight_dump ONLY when the per-reason rate limit
        # admits this dump: the explain dispatches (an (L+1)-row debug
        # kernel call) are the expensive part of the capture
        if explain_fn is not None:
            try:
                capture["explain"] = explain_fn()
            except Exception as e:  # noqa: BLE001
                capture["explain_error"] = repr(e)
        return {"slow_query": capture}

    try:
        capture["flight_record"] = flight_dump("slow_query", extra=extra)
    except Exception:  # noqa: BLE001
        capture["flight_record"] = None
    with _lock:
        _SLOW.append(capture)
    get_registry().observe("querylog.slow_capture",
                           time.perf_counter() - t0)


def recent(n: int | None = None) -> list[dict]:
    """Ring contents, oldest first (`n` newest when given)."""
    with _lock:
        out = list(_RING)
    return out[-n:] if n else out


def slow_recent(n: int | None = None) -> list[dict]:
    """Slow-query captures, oldest first (`n` newest when given)."""
    with _lock:
        out = list(_SLOW)
    return out[-n:] if n else out


def slow_header_entries(limit: int = 8) -> list[dict]:
    """Compact last-K slow entries for flight-record headers (query
    hash, level, stage split) — a breach dump is self-contained without
    a separate /querylog scrape. Reads the immutable header stamps, so
    a dump racing an in-flight capture serializes safely AND includes
    the offender that triggered it."""
    with _lock:
        out = list(_slow_headers)
    return out[-limit:]


def slow_last_60s() -> int:
    """Slow queries trapped in the trailing minute (the /healthz
    liveness window, sibling of recompiles_last_60s)."""
    cutoff = time.monotonic() - 60.0
    with _lock:
        return sum(1 for t in _slow_times if t >= cutoff)


def summary() -> dict:
    """The scrape-surface header: config + counts (entries ride
    separately via recent()/slow_recent())."""
    reg = get_registry()
    with _lock:
        ring_len, slow_len = len(_RING), len(_SLOW)
        ring_cap, slow_cap = _RING.maxlen, _SLOW.maxlen
    return {
        "enabled": _ENABLED,
        "sample": _SAMPLE_N,
        "redact": _REDACT,
        "slow_query_ms": _SLOW_MS,
        "ring": {"entries": ring_len, "capacity": ring_cap},
        "slow": {"entries": slow_len, "capacity": slow_cap,
                 "last_60s": slow_last_60s()},
        "recorded": reg.get("querylog.recorded"),
        "slow_trapped": reg.get("querylog.slow"),
    }


def clear() -> None:
    """Drop entries + captures (test isolation via obs.reset_all)."""
    global _seq
    with _lock:
        _RING.clear()
        _SLOW.clear()
        _slow_headers.clear()
        _slow_times.clear()
        _seq = 0
