"""Fixed-memory multi-resolution telemetry history (ISSUE 19).

Every other obs surface is an instant or a delta: /metrics is the
current cumulative state, /healthz a point-in-time card, bench rows a
whole-run aggregate. This module is the time axis — a background
sampler snapshots the TelemetryRegistry every TPU_IR_TS_SAMPLE_S and
stores the *window deltas* in ring tiers, so "what did routed p99 /
occupancy / cache-hit rate look like over the last hour, and is right
now anomalous?" has an answer that costs a bounded, constant number of
bytes no matter how long the process lives.

Design invariants:

- **Windows hold raw materials, never derived values.** A window is
  {counter deltas, gauge levels, histogram bucket deltas}. Rates and
  percentiles are computed at read time from the raw window. That is
  what makes downsampling exact: counter deltas and bucket counts are
  associative under addition (the same argument as
  aggregate.merge_snapshots), so merging K fine windows into one
  coarse window is bit-identical to having sampled at the coarse rate
  directly — no lossy pre-aggregation anywhere in the path.
- **Two merge directions, two duration rules.** Downsampling in TIME
  (fine tier -> coarse tier, one process) sums window durations; a
  rate over the merged window divides the summed deltas by the summed
  seconds. Merging across PROCESSES (cluster view through the spool)
  adds deltas for the *same* wall window, so the duration is the max,
  not the sum — cluster throughput is the sum of per-process rates.
- **Fixed memory.** Only the curated series (CURATED below) are
  retained, each tier is a deque(maxlen=capacity), and rollup staging
  buffers are bounded by the tier factor. ring_limits() states the
  declared bound; tests pin that the serialized footprint stops
  growing once the rings are full.

On top of the store ride the two consumers the history exists for:

- detect_anomalies(): a robust MAD z-score of each curated series'
  newest point against its same-tier history — median/MAD instead of
  mean/stddev so the detector is not poisoned by the very outliers it
  hunts. Detections increment ``timeseries.anomaly`` and write a
  rate-limited ``anomaly`` flight record (recorder's per-reason
  interval gives "loud exactly once" under a sustained fault).
- Forecaster: a least-squares sinusoid fit (period scan x linear
  phase/offset solve) over the occupancy series. The serving
  workload's diurnal burst pacing is sinusoidal (serving/workload.py),
  so phase and period are recoverable from less than one full cycle;
  the fit publishes ``forecast_occupancy`` — predicted occupancy
  TPU_IR_SCALE_LEAD_S in the future — which the Autoscaler consumes
  as its third scale-up signal (reason "forecast"), starting growth
  *before* the predicted burst instead of after the queue builds.
"""

from __future__ import annotations

import json
import math
import os
import socket
import threading
import time
from collections import deque

from ..utils import envvars
from .histogram import NUM_BUCKETS, percentile_from_counts
from .registry import GAUGE_MERGE, get_registry

# ---------------------------------------------------------------------------
# curated series
# ---------------------------------------------------------------------------

# (label, kind, source, anomaly_floor) — kind selects the read-time
# conversion: "rate" = counter delta / window seconds, "gauge" = level,
# "p50"/"p95"/"p99" = percentile from the window's bucket deltas (ms).
# anomaly_floor is the minimum deviation scale the MAD z-score divides
# by, in the series' own units — it keeps a near-constant series (MAD
# ~= 0) from turning ordinary jitter into infinite z.
CURATED = (
    ("submitted_per_s", "rate", "serving.submitted", 1.0),
    ("routed_per_s", "rate", "router.requests", 1.0),
    ("shed_per_s", "rate", "router.shed", 1.0),
    ("cache_hit_per_s", "rate", "cache.hit", 1.0),
    ("request_p50_ms", "p50", "request", 2.0),
    ("request_p99_ms", "p99", "request", 5.0),
    ("routed_p99_ms", "p99", "router.request", 5.0),
    ("occupancy", "gauge", "router.occupancy", 0.1),
    ("forecast_occupancy", "gauge", "forecast_occupancy", 0.1),
    ("slo_burn_fast", "gauge", "slo.burn_fast", 0.25),
)

_WATCH_COUNTERS = tuple(sorted({s for _, k, s, _ in CURATED
                                if k == "rate"}))
_WATCH_GAUGES = tuple(sorted({s for _, k, s, _ in CURATED
                              if k == "gauge"}))
_WATCH_HISTS = tuple(sorted({s for _, k, s, _ in CURATED
                             if k in ("p50", "p95", "p99")}))

# tier i rolls up FACTORS[i] base samples per window and retains
# CAPACITIES[i] windows. At the default 10 s sample period that is
# 10s x 360 (1 h), 1m x 240 (4 h), 10m x 144 (24 h).
DEFAULT_TIERS = ((1, 360), (6, 240), (60, 144))

_MIN_ANOMALY_POINTS = 12


def _sample_s() -> float:
    return envvars.get_float("TPU_IR_TS_SAMPLE_S")


def enabled() -> bool:
    return envvars.get_bool("TPU_IR_TIMESERIES")


# ---------------------------------------------------------------------------
# windows
# ---------------------------------------------------------------------------


def _merge(windows, *, across: bool):
    """Fold windows into one. across=False is temporal downsampling
    (durations sum); across=True is the cluster fold of the same wall
    window on N processes (duration is the max). Everything else is
    identical: counter deltas and bucket counts add, gauges fold by
    their declared GAUGE_MERGE policy in end-time order."""
    ws = sorted(windows, key=lambda w: w["t"])
    out = {"t": ws[-1]["t"],
           "dur_s": (max(w["dur_s"] for w in ws) if across
                     else sum(w["dur_s"] for w in ws)),
           "c": {}, "g": {}, "h": {}}
    for w in ws:
        for name, delta in w["c"].items():
            out["c"][name] = out["c"].get(name, 0) + delta
        for name, level in w["g"].items():
            if GAUGE_MERGE.get(name) == "max" and name in out["g"]:
                out["g"][name] = max(out["g"][name], level)
            else:
                out["g"][name] = level       # "last": newest wins
        for name, (counts, sum_s) in w["h"].items():
            if name in out["h"]:
                have, have_s = out["h"][name]
                out["h"][name] = ([a + b for a, b in zip(have, counts)],
                                  have_s + sum_s)
            else:
                out["h"][name] = (list(counts), sum_s)
    return out


def merge_windows(windows):
    """Downsample: merge consecutive fine-tier windows into one coarse
    window. Exact by construction — see the module docstring."""
    return _merge(windows, across=False)


def merge_windows_across(windows):
    """Cluster fold: merge the same wall window observed by N
    processes (deltas add, the duration does not)."""
    return _merge(windows, across=True)


def window_value(window, kind: str, source: str):
    """Read one curated value out of a raw window; None when the
    window never saw that series (absent gauge, empty histogram)."""
    if kind == "rate":
        dur = window["dur_s"]
        return window["c"].get(source, 0) / dur if dur > 0 else None
    if kind == "gauge":
        return window["g"].get(source)
    ent = window["h"].get(source)
    if ent is None or sum(ent[0]) == 0:
        return None
    q = {"p50": 0.50, "p95": 0.95, "p99": 0.99}[kind]
    sec = percentile_from_counts(list(ent[0]), q)
    return None if sec is None else sec * 1000.0


# ---------------------------------------------------------------------------
# the store
# ---------------------------------------------------------------------------


class TimeseriesStore:
    """Ring-tiered window store. add_window() appends one base-rate
    window to tier 0 and cascades exact rollups into the coarser
    tiers; sample() builds that window by diffing the registry's raw
    collect_state() against the previous sample."""

    def __init__(self, tiers=DEFAULT_TIERS, sample_s: float | None = None):
        factors = [int(f) for f, _ in tiers]
        if factors[0] != 1:
            raise ValueError("tier 0 must have factor 1")
        for a, b in zip(factors, factors[1:]):
            if b % a != 0 or b <= a:
                raise ValueError(f"tier factors must nest: {factors}")
        self._tiers = tuple((int(f), int(c)) for f, c in tiers)
        self._rings = [deque(maxlen=c) for _, c in self._tiers]
        # staging buffer feeding tier k+1: holds tier-k windows until
        # factor[k+1]/factor[k] of them merge into one coarse window
        self._pending = [[] for _ in self._tiers]
        self._sample_s = float(sample_s if sample_s is not None
                               else _sample_s())
        self._prev = None            # last raw collect_state
        self._prev_t = None
        self._lock = threading.Lock()
        self._anomalies = deque(maxlen=32)
        self.last_fit = None         # newest Forecaster fit, if any

    # -- ingest ------------------------------------------------------------

    def add_window(self, window) -> None:
        reg = get_registry()
        with self._lock:
            self._rings[0].append(window)
            carry = window
            for k in range(1, len(self._tiers)):
                self._pending[k].append(carry)
                need = self._tiers[k][0] // self._tiers[k - 1][0]
                if len(self._pending[k]) < need:
                    break
                carry = merge_windows(self._pending[k])
                self._pending[k] = []
                self._rings[k].append(carry)
                reg.incr("timeseries.rollups")
            else:
                return

    def sample(self, now: float | None = None) -> dict | None:
        """Take one base-rate window: diff the registry's raw state
        against the previous sample. The first sample (and any sample
        straddling a registry reset or a process restart) only
        re-baselines — a delta against a zeroed or foreign baseline
        would be garbage."""
        reg = get_registry()
        now = time.time() if now is None else now
        state = reg.collect_state(reset=False)
        with self._lock:
            prev, prev_t = self._prev, self._prev_t
            self._prev, self._prev_t = state, now
            rebase = (prev is None
                      or state["resets"] != prev["resets"]
                      or state["run_id"] != prev["run_id"])
        if rebase:
            return None
        window = {"t": now, "dur_s": max(now - prev_t, 1e-9),
                  "c": {}, "g": {}, "h": {}}
        pc = prev["counters"]
        for name in _WATCH_COUNTERS:
            delta = state["counters"].get(name, 0) - pc.get(name, 0)
            if delta > 0:
                window["c"][name] = delta
        for name in _WATCH_GAUGES:
            if name in state["gauges"]:
                window["g"][name] = state["gauges"][name]
        ph = prev["histograms"]
        for name in _WATCH_HISTS:
            ent = state["histograms"].get(name)
            if ent is None:
                continue
            was = ph.get(name, {"counts": [0] * NUM_BUCKETS, "sum_s": 0.0})
            counts = [max(a - b, 0) for a, b in
                      zip(ent["counts"], was["counts"])]
            if sum(counts) > 0:
                window["h"][name] = (counts,
                                     max(ent["sum_s"] - was["sum_s"], 0.0))
        self.add_window(window)
        reg.incr("timeseries.samples")
        return window

    def reset(self) -> None:
        with self._lock:
            for ring in self._rings:
                ring.clear()
            self._pending = [[] for _ in self._tiers]
            self._prev = self._prev_t = None
            self._anomalies.clear()
            self.last_fit = None

    # -- read --------------------------------------------------------------

    def windows(self, tier: int = 0):
        with self._lock:
            return list(self._rings[tier])

    def points(self, kind: str, source: str, tier: int = 0,
               since: float | None = None):
        """[(end_time, value)] for one curated series on one tier;
        windows that never saw the series are skipped."""
        out = []
        for w in self.windows(tier):
            if since is not None and w["t"] < since:
                continue
            v = window_value(w, kind, source)
            if v is not None:
                out.append((w["t"], v))
        return out

    def tier_layout(self):
        return [{"tier": i, "factor": f, "capacity": c,
                 "window_s": self._sample_s * f,
                 "len": len(self._rings[i])}
                for i, (f, c) in enumerate(self._tiers)]

    def ring_limits(self) -> dict:
        """The declared memory bound: total retained windows can never
        exceed sum(capacity) + sum(rollup staging), independent of how
        long the process has been alive."""
        factors = [f for f, _ in self._tiers]
        staging = sum(b // a - 1 for a, b in zip(factors, factors[1:]))
        return {"max_windows": sum(c for _, c in self._tiers) + staging,
                "tiers": len(self._tiers)}

    def state(self) -> dict:
        """Serializable form — the spool exchange unit and the
        footprint the bounded-memory test measures."""
        with self._lock:
            return {
                "sample_s": self._sample_s,
                "tiers": [[f, c] for f, c in self._tiers],
                "rings": [[_window_wire(w) for w in ring]
                          for ring in self._rings],
                "pending": [[_window_wire(w) for w in pend]
                            for pend in self._pending],
            }

    # -- anomaly + surfacing ----------------------------------------------

    def detect_anomalies(self, tier: int = 0, *, z_threshold=None,
                         flight: bool = True):
        """MAD z-score of each curated series' newest point against
        its same-tier history. Returns the detections; each one bumps
        ``timeseries.anomaly`` and (rate-limited per the recorder's
        per-reason interval) writes an ``anomaly`` flight record."""
        z_max = (envvars.get_float("TPU_IR_TS_ANOMALY_Z")
                 if z_threshold is None else float(z_threshold))
        if z_max <= 0:
            return []
        found = []
        for label, kind, source, floor in CURATED:
            pts = self.points(kind, source, tier)
            if len(pts) < _MIN_ANOMALY_POINTS:
                continue
            history = [v for _, v in pts[:-1]]
            t_last, latest = pts[-1]
            med = _median(history)
            mad = _median([abs(v - med) for v in history])
            # 0.6745 rescales MAD to a stddev-equivalent for a normal
            # population; the floor keeps a flat series from alarming
            scale = max(mad / 0.6745, 0.05 * abs(med), floor)
            z = (latest - med) / scale
            if abs(z) < z_max:
                continue
            rec = {"series": label, "tier": tier, "t": t_last,
                   "value": round(latest, 4), "median": round(med, 4),
                   "z": round(z, 2)}
            found.append(rec)
            get_registry().incr("timeseries.anomaly")
            with self._lock:
                self._anomalies.append(rec)
            if flight:
                from .recorder import flight_dump
                flight_dump("anomaly", extra={"anomaly": rec})
        return found

    def recent_anomalies(self):
        with self._lock:
            return list(self._anomalies)


def _window_wire(w):
    return {"t": w["t"], "dur_s": w["dur_s"], "c": dict(w["c"]),
            "g": dict(w["g"]),
            "h": {n: [list(c), s] for n, (c, s) in w["h"].items()}}


def _window_unwire(w):
    return {"t": w["t"], "dur_s": w["dur_s"], "c": dict(w["c"]),
            "g": dict(w["g"]),
            "h": {n: (list(ent[0]), ent[1])
                  for n, ent in w["h"].items()}}


def _median(values):
    vs = sorted(values)
    n = len(vs)
    mid = n // 2
    return vs[mid] if n % 2 else 0.5 * (vs[mid - 1] + vs[mid])


# ---------------------------------------------------------------------------
# the diurnal forecaster (ROADMAP 5a)
# ---------------------------------------------------------------------------


class Forecaster:
    """Sinusoid phase/period fit over a gauge series, publishing the
    predicted level lead_s ahead as the ``forecast_occupancy`` gauge.

    The fit scans candidate periods and solves the linear
    [sin, cos, 1] least squares per candidate — amplitude, phase, and
    mean drop out of the best-residual winner. A quality gate (r2 and
    amplitude floors) keeps a flat or noisy series from publishing a
    confident forecast: below the gate the gauge falls back to the
    current level, which makes the forecast signal degrade to exactly
    the reactive signal, never something worse."""

    def __init__(self, store, lead_s: float | None = None,
                 interval_s: float | None = None,
                 series: str = "router.occupancy",
                 sample: bool = False):
        self.store = store
        self.lead_s = (envvars.get_float("TPU_IR_SCALE_LEAD_S")
                       if lead_s is None else float(lead_s))
        self.interval_s = (max(0.05, self.lead_s / 4.0)
                           if interval_s is None else float(interval_s))
        self.series = series
        self.sample = sample     # drive store.sample() from poll()
        self._t0 = None          # ignore windows older than first poll
        self._last = -1e18

    def poll(self, now: float | None = None) -> float | None:
        """Refit if due; returns the published forecast (None when not
        due or below the quality gate)."""
        now = time.time() if now is None else now
        if self._t0 is None:
            self._t0 = now
        if now - self._last < self.interval_s:
            return None
        self._last = now
        if self.sample:
            self.store.sample(now=now)
        pts = self.store.points("gauge", self.series, tier=0,
                                since=self._t0)
        fit = fit_sinusoid(pts)
        reg = get_registry()
        if fit is None:
            if pts:          # degrade to reactive: forecast = current
                reg.set_gauge("forecast_occupancy", pts[-1][1])
            return None
        value = max(0.0, predict(fit, now + self.lead_s))
        fit["lead_s"] = self.lead_s
        fit["forecast"] = round(value, 4)
        self.store.last_fit = fit
        reg.set_gauge("forecast_occupancy", value)
        reg.incr("forecast.fits")
        return value


def fit_sinusoid(points, min_r2: float = 0.25,
                 min_amplitude: float = 0.05) -> dict | None:
    """Least-squares sinusoid over [(t, v)]: scan candidate periods,
    solve mean + a sin(wt) + b cos(wt) per candidate, keep the lowest
    residual. Returns None below the quality gate (not enough points,
    weak fit, or negligible amplitude)."""
    if len(points) < 8:
        return None
    t0 = points[0][0]
    ts = [t - t0 for t, _ in points]
    vs = [v for _, v in points]
    span = ts[-1]
    if span <= 0:
        return None
    mean = sum(vs) / len(vs)
    var = sum((v - mean) ** 2 for v in vs)
    if var <= 0:
        return None
    dt = span / (len(ts) - 1)
    best = None
    # periods from a few samples up to 4x the observed span: less than
    # one full cycle of history still locks phase on a clean sinusoid.
    # Coarse geometric scan first, then a fine scan around the winner.
    p = max(4.0 * dt, 1e-6)
    periods = []
    while p <= span * 4.0:
        periods.append(p)
        p *= 1.25
    for refine in range(2):
        if refine:
            if best is None:
                return None
            center = best[1]
            periods = [center * (0.8 + 0.02 * i) for i in range(21)]
        best = _best_period(ts, vs, mean, periods, best)
    if best is None:
        return None
    resid, period, a, b = best
    r2 = 1.0 - resid / var
    amplitude = math.hypot(a, b)
    if r2 < min_r2 or amplitude < min_amplitude:
        return None
    return {"period_s": round(period, 4), "a": a, "b": b,
            "mean": mean, "t0": t0,
            "amplitude": round(amplitude, 4), "r2": round(r2, 4)}


def _best_period(ts, vs, mean, periods, best):
    for period in periods:
        w = 2.0 * math.pi / period
        sa = ca = saa = cca = sca = sv = cv = 0.0
        for t, v in zip(ts, vs):
            s, c = math.sin(w * t), math.cos(w * t)
            sa += s
            ca += c
            saa += s * s
            cca += c * c
            sca += s * c
            sv += s * (v - mean)
            cv += c * (v - mean)
        n = float(len(ts))
        # normal equations for v - mean ~= a*sin + b*cos (centered)
        m11, m12, m22 = saa - sa * sa / n, sca - sa * ca / n, \
            cca - ca * ca / n
        det = m11 * m22 - m12 * m12
        if abs(det) < 1e-12:
            continue
        r1 = sv - sa * sum(v - mean for v in vs) / n
        r2_ = cv - ca * sum(v - mean for v in vs) / n
        a = (r1 * m22 - r2_ * m12) / det
        b = (r2_ * m11 - r1 * m12) / det
        resid = sum((v - mean - a * math.sin(w * t)
                     - b * math.cos(w * t)) ** 2
                    for t, v in zip(ts, vs))
        if best is None or resid < best[0]:
            best = (resid, period, a, b)
    return best


def predict(fit: dict, t: float) -> float:
    w = 2.0 * math.pi / fit["period_s"]
    dt = t - fit["t0"]
    return (fit["mean"] + fit["a"] * math.sin(w * dt)
            + fit["b"] * math.cos(w * dt))


# ---------------------------------------------------------------------------
# the background sampler
# ---------------------------------------------------------------------------


class TimeseriesSampler:
    """The named-daemon sampler: one store.sample() + anomaly sweep
    per interval. Same thread discipline as aggregate.SpoolWriter —
    daemon, "tpu-ir-obs-" prefixed (the conftest leak guard covers the
    prefix), Event-based stop() that takes a final sample so shutdown
    never loses the last window."""

    def __init__(self, store=None, interval_s: float | None = None):
        self.store = store if store is not None else get_store()
        self.interval_s = float(interval_s if interval_s is not None
                                else _sample_s())
        self._stop = threading.Event()
        self._thread = None

    def start(self) -> "TimeseriesSampler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="tpu-ir-obs-timeseries",
                daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.store.sample()
                self.store.detect_anomalies()
            except Exception:  # noqa: BLE001 — sampling must not die
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        try:
            self.store.sample()
        except Exception:  # noqa: BLE001
            pass


# ---------------------------------------------------------------------------
# process-global store + refcounted sampler (MetricsServer lifecycle)
# ---------------------------------------------------------------------------

_lock = threading.RLock()   # ensure_sampler -> get_store re-enters
_store: TimeseriesStore | None = None
_sampler: TimeseriesSampler | None = None
_sampler_refs = 0


def get_store() -> TimeseriesStore:
    global _store
    with _lock:
        if _store is None:
            _store = TimeseriesStore()
        return _store


def ensure_sampler() -> TimeseriesSampler | None:
    """Refcounted start — each MetricsServer.start() holds one ref;
    the thread stops when the last server releases. No-op (returns
    None) when TPU_IR_TIMESERIES=0, the rollback switch."""
    global _sampler, _sampler_refs
    if not enabled():
        return None
    with _lock:
        _sampler_refs += 1
        if _sampler is None:
            _sampler = TimeseriesSampler(store=None).start()
        return _sampler


def release_sampler() -> None:
    global _sampler, _sampler_refs
    with _lock:
        if _sampler_refs > 0:
            _sampler_refs -= 1
        sampler, done = _sampler, _sampler_refs == 0
        if done:
            _sampler = None
    if done and sampler is not None:
        sampler.stop()


def reset() -> None:
    """obs.reset_all() hook: drop history and baselines, keep any
    running sampler (servers own that lifecycle)."""
    global _store
    with _lock:
        store = _store
    if store is not None:
        store.reset()


# ---------------------------------------------------------------------------
# surfaces: /timeseries payload, flight header, cluster spool
# ---------------------------------------------------------------------------


def payload(cluster: bool = False) -> dict:
    """The /timeseries JSON: tier layout, every curated series as
    [t, value] points per tier, recent anomalies, and the newest
    forecast fit. cluster=True folds the spooled per-process stores
    into the local one first (deltas add, durations don't)."""
    if not enabled():
        return {"enabled": False}
    store = get_store()
    rings = [store.windows(i) for i in range(len(store.tier_layout()))]
    sources = 1
    if cluster:
        rings, sources = _cluster_rings(store, rings)
    series = {}
    for label, kind, source, _ in CURATED:
        tiers = []
        for ring in rings:
            pts = []
            for w in ring:
                v = window_value(w, kind, source)
                if v is not None:
                    pts.append([round(w["t"], 3), round(v, 4)])
            tiers.append(pts)
        series[label] = {"kind": kind, "source": source, "tiers": tiers}
    return {"enabled": True,
            "cluster": bool(cluster), "sources": sources,
            "tiers": store.tier_layout(),
            "ring_limits": store.ring_limits(),
            "series": series,
            "anomalies": store.recent_anomalies(),
            "forecast": store.last_fit}


def header_window(limit: int = 32) -> dict | None:
    """The flight-record header section: the last-N tier-0 points per
    curated series, so every post-mortem ships its own lead-up."""
    if not enabled():
        return None
    store = get_store()
    out = {}
    for label, kind, source, _ in CURATED:
        pts = store.points(kind, source, tier=0)[-limit:]
        if pts:
            out[label] = [[round(t, 3), round(v, 4)] for t, v in pts]
    if not out:
        return None
    return {"window_s": store.tier_layout()[0]["window_s"],
            "series": out}


def _spool_path(out_dir: str) -> str:
    host = socket.gethostname().replace("/", "_") or "host"
    return os.path.join(out_dir, f"timeseries-{host}-{os.getpid()}.json")


def spool_write_store(out_dir: str | None = None) -> str | None:
    """One live file per process (newest state wins by overwrite),
    alongside the telemetry snapshot spool; aggregate.SpoolWriter
    calls this on the same cadence."""
    from .aggregate import spool_dir
    d = out_dir or spool_dir()
    if d is None or not enabled():
        return None
    try:
        os.makedirs(d, exist_ok=True)
        path = _spool_path(d)
        doc = {"run_id": get_registry().run_id, "pid": os.getpid(),
               "host": socket.gethostname(), "time": time.time(),
               "store": get_store().state()}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path
    except Exception:  # noqa: BLE001 — spooling is best-effort
        return None


def read_spool_stores(out_dir: str | None = None) -> list:
    from .aggregate import spool_dir
    d = out_dir or spool_dir()
    if d is None or not os.path.isdir(d):
        return []
    docs = []
    for name in sorted(os.listdir(d)):
        if not (name.startswith("timeseries-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(d, name)) as f:
                docs.append(json.load(f))
        except Exception:  # noqa: BLE001 — torn write mid-replace
            continue
    return docs


def _cluster_rings(store, rings):
    """Fold spooled per-process rings into the local ones: windows
    aligning on the same nominal wall bucket merge across processes."""
    my_run = get_registry().run_id
    layout = store.tier_layout()
    buckets = [dict() for _ in layout]
    sources = 1
    for tier, ring in enumerate(rings):
        win_s = max(layout[tier]["window_s"], 1e-9)
        for w in ring:
            buckets[tier].setdefault(round(w["t"] / win_s), []).append(w)
    for doc in read_spool_stores():
        if doc.get("run_id") == my_run:
            continue                      # the local store, spooled
        sources += 1
        for tier, ring in enumerate(doc.get("store", {}).get("rings", [])):
            if tier >= len(buckets):
                break
            win_s = max(layout[tier]["window_s"], 1e-9)
            for wire in ring:
                w = _window_unwire(wire)
                buckets[tier].setdefault(
                    round(w["t"] / win_s), []).append(w)
    merged = []
    for tier_buckets in buckets:
        merged.append([merge_windows_across(ws)
                       for _, ws in sorted(tier_buckets.items())])
    return merged, sources
