"""The embedded metrics/jobs HTTP server — the JobTracker web UI, reborn.

One stdlib `ThreadingHTTPServer` (no dependencies, daemon request
threads, ephemeral-port friendly) exposes the process's telemetry while
it runs:

  GET /metrics          Prometheus text exposition (the registry's
                        prometheus_text — scrape it with anything).
                        Read-only BY CONSTRUCTION: a `?reset=1` query is
                        rejected 403 — a scraper must never drain the
                        intervals the process's own delta consumers
                        (serve-bench's latency section) are measuring.
  GET /metrics.json     The registry snapshot as JSON (schema/seq/resets
                        stamped, so pollers detect third-party resets).
  GET /healthz          JSON liveness + serving control-plane state:
                        breaker state, ladder level, admission queue
                        depth (from the live ServingFrontends that
                        registered themselves), plus running-job count.
  GET /jobs             The JobTracker job table (obs/progress.py) as
                        JSON; `?format=html` renders the minimal HTML
                        table echoing the reference's saved pages.
  GET /jobs/<id>        One job, JSON or `?format=html`.
  GET /profile          The device-cost profiling report
                        (obs/profiling.py): per-signature compile
                        counts + FLOPs/bytes, the dispatch time split,
                        memory gauges, recompile window.
  GET /querylog         The sampled query log (obs/querylog.py):
                        config + ring entries + slow-query captures
                        (`?slow=1` captures only, `?n=N` newest N).
  GET /doctor           Index health reports for the index dirs this
                        process loaded (index/doctor.py: df skew, shard
                        balance, tier occupancy, arena section sizes);
                        `?index=PATH` narrows to one registered dir.
  GET /flight           Recent flight-recorder artifact headers
                        (reason/time/seq/path), newest first.

Every `?format=html` page shares one nav row, so the JobTracker-style
pages cross-link (/jobs <-> /cluster <-> /profile <-> /querylog <->
/doctor) instead of each being a dead end.
  GET /cluster          The spool-merged cluster view (this process's
                        live registry folded in) when
                        TPU_IR_TELEMETRY_DIR is configured.

`MetricsServer.start(port)` binds (port 0 = ephemeral, `.port` tells
you what you got), serves on a named daemon thread, and optionally runs
a SpoolWriter when a telemetry spool dir is configured; `.stop()` joins
both — the tests' thread-leak guard fails anything that forgets.
Wired in via `tpu-ir serve-bench --metrics-port` and the build
commands' `--track PORT`.
"""

from __future__ import annotations

import html
import json
import logging
import os
import threading
import time
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from . import progress
from .recorder import recent_headers
from .registry import get_registry

logger = logging.getLogger(__name__)

# -- health sources ---------------------------------------------------------

_health_lock = threading.Lock()
_frontends: list = []  # weakrefs to live ServingFrontends, oldest first
_routers: list = []    # weakrefs to live routers (serving/router.py)
_autoscalers: list = []  # weakrefs to live Autoscalers (serving/autoscale.py)
_index_dirs: list = []  # index dirs this process loaded, oldest first
_MAX_INDEX_DIRS = 4
_doctor_cache: dict = {}  # dir -> (metadata mtime_ns, report)


def register_health_source(frontend) -> None:
    """Called by ServingFrontend.__init__: /healthz reports the breaker /
    ladder / queue state of every frontend still alive. Weakrefs — the
    server must never keep a dead frontend's scorer resident."""
    with _health_lock:
        _frontends.append(weakref.ref(frontend))


def register_router(router) -> None:
    """Called by serving/router.py Router.__init__: /healthz aggregates
    the whole shard topology — per shard, each replica's liveness /
    breaker state / trailing latency plus the worker's own /healthz
    payload (polled, TTL-cached) — instead of only the weakref-
    registered in-process frontends. Weakref, like the frontends: the
    server must never keep a closed router's connections alive."""
    with _health_lock:
        _routers.append(weakref.ref(router))


def register_autoscaler(autoscaler) -> None:
    """Called by serving/autoscale.py Autoscaler.__init__: /healthz
    reports the elastic-membership control loop — membership epoch,
    per-replica lifecycle, hysteresis counters, and the last scaling
    decision with its reason (ISSUE 16). Weakref, like the routers."""
    with _health_lock:
        _autoscalers.append(weakref.ref(autoscaler))


def register_index_dir(path) -> None:
    """Called by Scorer.load: /doctor introspects the index dirs THIS
    process actually serves — the endpoint never reads an arbitrary
    caller-supplied path, only registered ones (last-K distinct)."""
    import os

    path = os.path.abspath(path)
    with _health_lock:
        if path in _index_dirs:
            _index_dirs.remove(path)
        _index_dirs.append(path)
        del _index_dirs[:-_MAX_INDEX_DIRS]
        # evict cached reports for rotated-out dirs: a long-lived
        # process cycling through many indexes must not pin one full
        # doctor report per ever-seen dir
        for stale in [d for d in _doctor_cache if d not in _index_dirs]:
            del _doctor_cache[stale]


def registered_index_dirs() -> list:
    with _health_lock:
        return list(_index_dirs)


def _doctor_payload(query: dict) -> dict:
    """/doctor body: one health report per registered index dir (newest
    first), cached by metadata mtime — the report reads every shard
    header, which must not re-run per scrape. `?index=PATH` narrows to
    one REGISTERED dir (unregistered paths are refused, not read)."""
    import os

    from ..index.doctor import doctor_report

    dirs = list(reversed(registered_index_dirs()))
    want = query.get("index", [None])[0]
    if want is not None:
        want = os.path.abspath(want)
        if want not in dirs:
            return {"error": f"{want} is not a registered index dir",
                    "registered": dirs}
        dirs = [want]
    if not dirs:
        return {"error": "no index loaded in this process yet",
                "indexes": {}}
    out = {}
    for d in dirs:
        try:
            stamp = _doctor_stamp(d)
            with _health_lock:
                cached = _doctor_cache.get(d)
            if cached is not None and cached[0] == stamp:
                out[d] = cached[1]
                continue
            report = doctor_report(d)
            with _health_lock:
                _doctor_cache[d] = (stamp, report)
            out[d] = report
        except Exception as e:  # noqa: BLE001 — one sick index must not
            out[d] = {"error": repr(e)}  # hide the others' reports
    return {"indexes": out}


def _doctor_stamp(d: str):
    """Cache-invalidation stamp for one index dir: metadata.json mtime
    PLUS the serving-cache dirs' mtimes — `tpu-ir warm` writes a new
    serving-*/ without touching metadata.json, and the report's
    serving_caches section must not stay stale for the process's life."""
    import os

    stamp = [os.stat(os.path.join(d, "metadata.json")).st_mtime_ns]
    try:
        for name in sorted(os.listdir(d)):
            if name.startswith("serving-"):
                stamp.append(
                    (name, os.stat(os.path.join(d, name)).st_mtime_ns))
    except OSError:
        pass
    return tuple(stamp)


def _live_frontends() -> list:
    with _health_lock:
        alive = [(r, r()) for r in _frontends]
        _frontends[:] = [r for r, f in alive if f is not None]
        return [f for _, f in alive if f is not None]


def _live_routers() -> list:
    with _health_lock:
        alive = [(r, r()) for r in _routers]
        _routers[:] = [r for r, f in alive if f is not None]
        return [f for _, f in alive if f is not None]


def _live_autoscalers() -> list:
    with _health_lock:
        alive = [(r, r()) for r in _autoscalers]
        _autoscalers[:] = [r for r, f in alive if f is not None]
        return [f for _, f in alive if f is not None]


# process identity (ISSUE 19 satellite): a scraper comparing two
# /healthz reads needs to tell "this process restarted" from "someone
# reset the registry" without diffing seq/resets heuristics. started_at
# + uptime_s pin the process lifetime; build_sha pins WHICH build is
# running — stamped the same way BENCH_HISTORY rows are (git rev-parse
# at first ask, cached: health scrapes must not fork per request).
_PROCESS_START = time.time()
_PROCESS_START_ISO = time.strftime("%Y-%m-%dT%H:%M:%S",
                                   time.localtime(_PROCESS_START))
_BUILD_SHA: list = []


def _build_sha() -> str | None:
    if not _BUILD_SHA:
        try:
            import subprocess

            sha = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                cwd=os.path.dirname(os.path.abspath(__file__)),
                capture_output=True, text=True, timeout=10).stdout.strip()
        except Exception:  # noqa: BLE001 — health must not 500
            sha = ""
        _BUILD_SHA.append(sha or None)
    return _BUILD_SHA[0]


def health_snapshot() -> dict:
    """The /healthz payload. The newest live frontend's control-plane
    state is lifted to the top-level `breaker`/`ladder`/`queue_depth`
    keys (the fields an alerting rule matches on); every live frontend
    appears under `frontends`."""
    fes = _live_frontends()
    running = [j for j in progress.jobs() if j.state == "running"]
    out = {
        "status": "ok",
        "breaker": None,
        "ladder": None,
        "queue_depth": None,
        "frontends": [],
        "jobs_running": len(running),
        "registry_seq": get_registry().seq,
        "uptime_s": round(time.time() - _PROCESS_START, 3),
        "started_at": _PROCESS_START_ISO,
        "build_sha": _build_sha(),
    }
    try:
        # a recompile storm in progress is a liveness problem (every
        # affected dispatch pays seconds of XLA); surface the trailing
        # window where the alerting rules already look
        from .profiling import recompiles_last_60s

        out["recompiles_last_60s"] = recompiles_last_60s()
    except Exception:  # noqa: BLE001 — health must not 500
        out["recompiles_last_60s"] = None
    try:
        # same trailing window for the slow-query trap: a latency
        # incident shows here before any percentile moves
        from .querylog import slow_last_60s

        out["slow_queries_last_60s"] = slow_last_60s()
    except Exception:  # noqa: BLE001 — health must not 500
        out["slow_queries_last_60s"] = None
    try:
        # durable-ingest counters (ISSUE 17): this process's write-path
        # health — WAL append/fsync volume (their ratio is the batching
        # dial's readout), torn-tail scars, records replayed by crash
        # recovery, and the single-writer lease verdicts. A nonzero
        # `replayed` means a writer in this process recovered a crash;
        # a climbing `lease_conflicts` means something keeps trying to
        # double-write a live dir.
        reg = get_registry()
        out["ingest"] = {
            "wal_appends": reg.get("ingest.wal_appends"),
            "wal_fsyncs": reg.get("ingest.wal_fsyncs"),
            "wal_torn_tail_truncated": reg.get(
                "ingest.wal_torn_tail_truncated"),
            "wal_segments_retired": reg.get("ingest.wal_segments_retired"),
            "replayed": reg.get("ingest.replayed"),
            "lease_takeovers": reg.get("ingest.lease_takeovers"),
            "lease_conflicts": reg.get("ingest.lease_conflicts"),
            "flushes": reg.get("ingest.flushes"),
            # flush-commit -> first-servable-query lag (ISSUE 18
            # satellite): the freshness number an operator previously
            # only saw as an ingest-soak bench row, live
            "freshness_lag_ms": reg.gauges().get(
                "ingest.freshness_lag_ms"),
        }
    except Exception:  # noqa: BLE001 — health must not 500
        out["ingest"] = None
    for fe in fes:
        try:
            st = fe.stats()
        except Exception as e:  # noqa: BLE001 — health must not 500
            st = {"error": repr(e)}
        out["frontends"].append(st)
    routers = _live_routers()
    if routers:
        # the scatter-gather topology (ISSUE 10): shard id -> replica
        # states / breakers / worker health / generation, aggregated by
        # the newest live router (TTL-cached worker polls inside)
        try:
            out["shards"] = routers[-1].health_summary()
        except Exception as e:  # noqa: BLE001 — health must not 500
            out["shards"] = {"error": repr(e)}
    scalers = _live_autoscalers()
    if scalers:
        # the elastic-membership control loop (ISSUE 16): epoch,
        # per-replica lifecycle, last decision + reason — the page an
        # operator reads to answer "why did the fleet just grow?"
        try:
            out["autoscaler"] = scalers[-1].snapshot()
        except Exception as e:  # noqa: BLE001 — health must not 500
            out["autoscaler"] = {"error": repr(e)}
    if out["frontends"]:
        latest = out["frontends"][-1]
        out["breaker"] = latest.get("breaker")
        out["ladder"] = latest.get("ladder")
        out["queue_depth"] = latest.get("queue_depth")
        # the result-cache tier (ISSUE 15): hit fraction collapsing
        # under steady traffic is an alerting-grade signal (the cache
        # disengaged), so the newest frontend's view rides top-level;
        # router caches appear under shards.cache
        out["cache"] = latest.get("cache")
        # the live-index generation this process currently serves
        # (ISSUE 12) — the rolling-swap driver confirms handoffs here
        out["generation"] = latest.get("generation")
        # the coalescer's control-plane state (ISSUE 9): occupancy
        # collapsing to ~1 under load means batching silently
        # disengaged — an alerting-grade signal, so it rides top-level
        out["batching"] = latest.get("batching")
    return out


# -- the JobTracker HTML echo ----------------------------------------------

# every HTML page carries the same nav row, so the JobTracker-style
# pages cross-link instead of each being a dead end (satellite: the
# /jobs <-> /cluster <-> /profile <-> /querylog <-> /doctor drift fix)
_NAV_ROUTES = ("/healthz", "/jobs?format=html", "/cluster?format=html",
               "/profile?format=html", "/querylog?format=html",
               "/doctor?format=html", "/slo?format=html",
               "/timeseries?format=html", "/flight", "/metrics")


def _nav_html() -> str:
    links = " &middot; ".join(
        f"<a href='{r}'>{html.escape(r.split('?')[0])}</a>"
        for r in _NAV_ROUTES)
    return f"<p class='nav'>{links}</p>"


_STYLE = ("<style>body{font-family:sans-serif;margin:1em}"
          "table{border-collapse:collapse;margin:0 0 1.5em}"
          "td,th{border:1px solid #999;padding:2px 8px;text-align:left}"
          "th{background:#ddd}.pct{font-weight:bold}"
          "pre{background:#f4f4f4;padding:8px;overflow-x:auto}"
          ".nav{margin:0 0 1em}</style>")


def _json_page_html(title: str, obj) -> str:
    """Minimal HTML rendering of a JSON payload (nav + <pre>): the
    /profile /cluster /querylog /doctor pages — one shape, one place."""
    body = html.escape(json.dumps(obj, indent=2, default=repr))
    return ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
            f"<title>{html.escape(title)}</title>{_STYLE}</head><body>"
            f"<h1>{html.escape(title)}</h1>{_nav_html()}"
            f"<pre>{body}</pre></body></html>")


def _timeseries_html(payload: dict) -> str:
    """The /timeseries sparkline dashboard: one inline-SVG polyline
    per curated series per tier — the retained history at a glance,
    JobTracker-page idiom (static HTML, no scripts to serve)."""
    parts = [
        "<!DOCTYPE html><html><head><meta charset='utf-8'>",
        f"<title>tpu-ir timeseries</title>{_STYLE}</head><body>",
        "<h1>tpu-ir timeseries</h1>", _nav_html(),
    ]
    if not payload.get("enabled"):
        parts.append("<p>timeseries disabled (TPU_IR_TIMESERIES=0)</p>"
                     "</body></html>")
        return "".join(parts)
    tiers = payload.get("tiers", [])
    parts.append("<p>" + " &middot; ".join(
        f"tier {t['tier']}: {t['window_s']:g}s &times; "
        f"{t['capacity']} ({t['len']} held)" for t in tiers) + "</p>")
    w, h = 360, 48
    for label in sorted(payload.get("series", {})):
        ent = payload["series"][label]
        cells = []
        for tier_pts in ent["tiers"]:
            vals = [v for _, v in tier_pts]
            if not vals:
                cells.append("<td>(no data)</td>")
                continue
            lo, hi = min(vals), max(vals)
            span = (hi - lo) or 1.0
            n = max(len(vals) - 1, 1)
            pts = " ".join(
                f"{i * w / n:.1f},{h - (v - lo) / span * h:.1f}"
                for i, v in enumerate(vals))
            cells.append(
                f"<td><svg width='{w}' height='{h}' "
                f"viewBox='0 0 {w} {h}'><polyline points='{pts}' "
                "fill='none' stroke='#36c' stroke-width='1.5'/></svg>"
                f"<br><small>last {vals[-1]:g} "
                f"[{lo:g}..{hi:g}]</small></td>")
        parts.append(f"<h3>{html.escape(label)}</h3>"
                     f"<table><tr>{''.join(cells)}</tr></table>")
    anomalies = payload.get("anomalies") or []
    if anomalies:
        rows = "".join(
            f"<tr><td>{html.escape(str(a['series']))}</td>"
            f"<td>{a['z']}</td><td>{a['value']}</td>"
            f"<td>{a['median']}</td></tr>" for a in anomalies)
        parts.append("<h3>anomalies</h3><table><tr><th>series</th>"
                     "<th>z</th><th>value</th><th>median</th></tr>"
                     f"{rows}</table>")
    fit = payload.get("forecast")
    if fit:
        parts.append(
            f"<p>forecast: period {fit['period_s']:g}s, amplitude "
            f"{fit['amplitude']:g}, r&sup2; {fit['r2']:g} &rarr; "
            f"occupancy {fit.get('forecast', 0.0):g} in "
            f"{fit.get('lead_s', 0.0):g}s</p>")
    parts.append("</body></html>")
    return "".join(parts)


def _jobs_html(job_dicts: list, title: str) -> str:
    """A minimal single-page echo of the reference's saved JobTracker
    pages: one table per job — name/state/percent header row, then one
    row per phase with its task counts and counters."""
    parts = [
        "<!DOCTYPE html><html><head><meta charset='utf-8'>",
        f"<title>{html.escape(title)}</title>",
        _STYLE,
        f"</head><body><h1>{html.escape(title)}</h1>",
        _nav_html(),
    ]
    for d in job_dicts:
        eta = f" &middot; ETA {d['eta_s']}s" if "eta_s" in d else ""
        parts.append(
            f"<h2><a href='/jobs/{d['job_id']}?format=html'>"
            f"job_{d['job_id']:04d}</a> {html.escape(d['name'])} "
            f"({html.escape(d['kind'])})</h2>"
            f"<p>state: <b>{html.escape(d['state'])}</b> &middot; "
            f"<span class='pct'>{d['percent']}% complete</span> &middot; "
            f"{d['elapsed_s']}s elapsed{eta}</p>")
        parts.append("<table><tr><th>phase</th><th>done</th><th>total</th>"
                     "<th>%</th><th>counters</th></tr>")
        for ph in d["phases"]:
            counters = ", ".join(f"{k}={v}"
                                 for k, v in sorted(ph["counters"].items()))
            pct = f"{ph['percent']}%" if "percent" in ph else ""
            parts.append(
                f"<tr><td>{html.escape(ph['phase'])}</td>"
                f"<td>{ph['done']}</td>"
                f"<td>{'' if ph['total'] is None else ph['total']}</td>"
                f"<td>{pct}</td><td>{html.escape(counters)}</td></tr>")
        parts.append("</table>")
    if not job_dicts:
        parts.append("<p>(no jobs recorded)</p>")
    parts.append("</body></html>")
    return "".join(parts)


def _trace_waterfall_html(st: dict) -> str:
    """The /trace/<id> page: one row per span, indented by depth, with
    an offset/width bar on the shared trace timeline — the JobTracker
    jobdetails.jsp of the distributed tier (ISSUE 18)."""
    total = max(float(st.get("dur_ms") or 0.0), 1e-9)
    start0 = float(st.get("start_ms") or 0.0)
    rows = []

    def walk(node: dict, depth: int) -> None:
        off = max(0.0, float(node.get("start_ms") or start0) - start0)
        dur = float(node.get("dur_ms") or 0.0)
        left = round(100.0 * off / total, 2)
        width = max(round(100.0 * dur / total, 2), 0.3)
        label = ("&nbsp;" * (depth * 3)) + html.escape(
            str(node.get("name", "?")))
        attrs = node.get("attrs") or {}
        att = html.escape(", ".join(f"{k}={v}"
                                    for k, v in sorted(attrs.items())))
        err = " style='color:#b00'" if node.get("error") else ""
        rows.append(
            f"<tr><td{err}>{label}</td>"
            f"<td>{html.escape(str(node.get('service', '?')))}</td>"
            f"<td>{node.get('dur_ms', 0.0)}</td>"
            f"<td><div style='position:relative;height:12px;"
            f"background:#eee'><div style='position:absolute;"
            f"left:{left}%;width:{width}%;height:12px;background:#69c'>"
            f"</div></div></td><td>{att}</td></tr>")
        for c in node.get("children", ()):
            walk(c, depth + 1)

    for r in st.get("roots", ()):
        walk(r, 0)
    tid = html.escape(str(st.get("trace_id", "?")))
    services = html.escape(", ".join(st.get("services", ())))
    return ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
            f"<title>trace {tid}</title>{_STYLE}</head><body>"
            f"<h1>trace {tid}</h1>{_nav_html()}"
            f"<p>{st.get('span_count', 0)} spans &middot; "
            f"{st.get('dur_ms', 0.0)} ms &middot; services: {services}</p>"
            "<table style='width:100%'><tr><th>span</th><th>service</th>"
            "<th>ms</th><th style='width:45%'>waterfall</th>"
            "<th>attrs</th></tr>" + "".join(rows)
            + "</table></body></html>")


# -- the server -------------------------------------------------------------


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # stdlib default prints to stderr
        logger.debug("metrics-http: " + fmt, *args)

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, obj, code: int = 200) -> None:
        self._send(code, json.dumps(obj, default=repr).encode("utf-8"),
                   "application/json")

    def _json_or_html(self, q: dict, title: str, obj) -> None:
        """JSON by default, the minimal nav-linked HTML page with
        `?format=html` — the shared shape of the introspection routes."""
        if q.get("format", [""])[0] == "html":
            self._send(200, _json_page_html(title, obj).encode("utf-8"),
                       "text/html; charset=utf-8")
        else:
            self._json(obj)

    def do_POST(self) -> None:  # noqa: N802 — stdlib handler contract
        """RPC surface for the scatter-gather tier (ISSUE 10): a shard
        WORKER process registers instance-scoped handlers
        (`MetricsServer(rpc_handlers={"search": fn, ...})`) and the
        router POSTs JSON to /rpc/<name>. Registration is per server
        instance — two in-process workers on different ports must not
        share one global handler table. Error contract: a structured
        Overloaded shed is 503 (the router retries another replica), any
        other failure is 500 with the repr (the router counts it as a
        replica failure)."""
        try:
            url = urlparse(self.path)
            route = url.path.rstrip("/")
            handlers = getattr(self.server, "rpc_handlers", None) or {}
            if not route.startswith("/rpc/"):
                self._json({"error": "unknown endpoint"}, code=404)
                return
            name = route[len("/rpc/"):]
            fn = handlers.get(name)
            if fn is None:
                self._json({"error": f"no rpc handler {name!r}"},
                           code=404)
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
            except (TypeError, ValueError):
                length = 0
            try:
                payload = json.loads(self.rfile.read(length) or b"{}")
            except ValueError:
                self._json({"error": "malformed JSON body"}, code=400)
                return
            # distributed tracing (ISSUE 18): adopt the caller's
            # traceparent so every span the handler's request opens
            # joins the caller's trace, then piggyback this process's
            # span batch on the response (`_trace`) — the router
            # stitches live, no spool round-trip on the serving path
            from . import disttrace

            ctx = disttrace.adopt(self.headers.get("traceparent"))
            try:
                with disttrace.use(ctx):
                    out = fn(payload)
                if ctx is not None and isinstance(out, dict):
                    batch = disttrace.piggyback(ctx.trace_id)
                    if batch:
                        out["_trace"] = batch
                self._json(out)
            except Exception as e:  # noqa: BLE001 — classified below
                # the serving Overloaded shed is structural, not a bug:
                # 503 tells the router "retry elsewhere", 500 "replica
                # failure" (import is lazy — obs must not import serving)
                from ..serving.admission import Overloaded

                if isinstance(e, Overloaded):
                    self._json({"error": "overloaded",
                                "reason": e.reason,
                                "level": e.level}, code=503)
                else:
                    self._json({"error": repr(e)}, code=500)
        except BrokenPipeError:
            pass  # caller hung up mid-response; its problem
        except Exception as e:  # noqa: BLE001 — an RPC must never kill
            try:                # the worker process it runs in
                self._json({"error": repr(e)}, code=500)
            except Exception:  # noqa: BLE001
                pass

    def do_GET(self) -> None:  # noqa: N802 — stdlib handler contract
        try:
            url = urlparse(self.path)
            q = parse_qs(url.query)
            route = url.path.rstrip("/") or "/"
            if route == "/metrics":
                if q.get("reset"):
                    self._json({"error": "scrapes are read-only; reset "
                                "via the owning process's CLI "
                                "(tpu-ir metrics --reset)"}, code=403)
                    return
                self._send(200,
                           get_registry().prometheus_text().encode("utf-8"),
                           "text/plain; version=0.0.4")
            elif route == "/metrics.json":
                self._json(get_registry().snapshot())
            elif route == "/healthz":
                payload = health_snapshot()
                extra = getattr(self.server, "extra_health", None)
                if extra is not None:
                    # worker identity (shard id, replica, doc range,
                    # generation) — the router's health aggregation and
                    # failover decisions read these fields
                    payload.update(extra() if callable(extra) else extra)
                self._json(payload)
            elif route == "/jobs":
                dicts = [j.to_dict() for j in reversed(progress.jobs())]
                if q.get("format", [""])[0] == "html":
                    self._send(200, _jobs_html(
                        dicts, "tpu-ir jobs").encode("utf-8"),
                        "text/html; charset=utf-8")
                else:
                    self._json({"jobs": dicts})
            elif route.startswith("/jobs/"):
                try:
                    job = progress.get_job(int(route.split("/", 2)[2]))
                except ValueError:
                    job = None
                if job is None:
                    self._json({"error": "no such job"}, code=404)
                    return
                d = job.to_dict()
                if q.get("format", [""])[0] == "html":
                    self._send(200, _jobs_html(
                        [d], f"tpu-ir job_{d['job_id']:04d}")
                        .encode("utf-8"), "text/html; charset=utf-8")
                else:
                    self._json(d)
            elif route == "/profile":
                from .profiling import profile_report

                self._json_or_html(q, "tpu-ir profile", profile_report())
            elif route == "/querylog":
                from . import querylog

                n = None
                if q.get("n"):
                    try:
                        n = max(int(q["n"][0]), 1)
                    except ValueError:
                        self._json({"error": "n must be an integer"},
                                   code=400)
                        return
                slow_only = q.get("slow", ["0"])[0] not in ("", "0",
                                                            "false")
                payload = {
                    **querylog.summary(),
                    "slow_entries": querylog.slow_recent(n),
                }
                if not slow_only:
                    payload["entries"] = querylog.recent(n)
                self._json_or_html(q, "tpu-ir querylog", payload)
            elif route == "/doctor":
                self._json_or_html(q, "tpu-ir doctor",
                                   _doctor_payload(q))
            elif route == "/slo":
                from . import disttrace

                self._json_or_html(q, "tpu-ir slo",
                                   disttrace.slo_snapshot())
            elif route == "/trace":
                from . import disttrace

                self._json({"traces": disttrace.trace_ids()})
            elif route.startswith("/trace/"):
                from . import disttrace

                tid = route.split("/", 2)[2]
                st = disttrace.stitch(tid)
                if st is None:
                    self._json({"error": f"no trace {tid!r}"}, code=404)
                    return
                if q.get("format", [""])[0] == "html":
                    self._send(200,
                               _trace_waterfall_html(st).encode("utf-8"),
                               "text/html; charset=utf-8")
                else:
                    self._json(st)
            elif route == "/timeseries":
                from . import timeseries

                cluster = q.get("cluster", ["0"])[0] not in ("", "0")
                payload = timeseries.payload(cluster=cluster)
                if q.get("format", [""])[0] == "html":
                    self._send(200,
                               _timeseries_html(payload).encode("utf-8"),
                               "text/html; charset=utf-8")
                else:
                    self._json(payload)
            elif route == "/flight":
                self._json({"flight_records": recent_headers()})
            elif route == "/cluster":
                from . import aggregate

                if not aggregate.spool_dir():
                    self._json({"error": "TPU_IR_TELEMETRY_DIR not set"},
                               code=404)
                    return
                self._json_or_html(q, "tpu-ir cluster",
                                   aggregate.merge_spool(
                                       include_local=True))
            elif route == "/":
                self._json({"endpoints": ["/metrics", "/metrics.json",
                                          "/healthz", "/jobs",
                                          "/jobs/<id>", "/profile",
                                          "/querylog", "/doctor",
                                          "/slo", "/trace",
                                          "/trace/<id>", "/timeseries",
                                          "/flight", "/cluster"]})
            else:
                self._json({"error": "unknown endpoint"}, code=404)
        except BrokenPipeError:
            pass  # scraper hung up mid-response; its problem
        except Exception as e:  # noqa: BLE001 — a scrape must never kill
            try:                # the serving process it observes
                self._json({"error": repr(e)}, code=500)
            except Exception:  # noqa: BLE001
                pass


class MetricsServer:
    """The embedded observability server: bind, serve on a named daemon
    thread, stop cleanly. Request threads are daemons too (a stuck
    scraper cannot block process exit), but stop() shuts the listener
    down and joins the serve thread — the orderly path every CLI wiring
    uses (try/finally)."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 spool: bool | None = None,
                 rpc_handlers: dict | None = None,
                 extra_health=None):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        # instance-scoped RPC handlers + health annotations (the shard
        # WORKER surface, ISSUE 10) — deliberately not module globals:
        # tests run several in-process workers on different ports
        self._httpd.rpc_handlers = dict(rpc_handlers or {})
        self._httpd.extra_health = extra_health
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: threading.Thread | None = None
        from . import aggregate

        want_spool = (spool if spool is not None
                      else aggregate.spool_dir() is not None)
        self._spool = aggregate.SpoolWriter() if want_spool else None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "MetricsServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                kwargs={"poll_interval": 0.1},
                name=f"tpu-ir-obs-http-{self.port}", daemon=True)
            self._thread.start()
            if self._spool is not None:
                self._spool.start()
            # the telemetry time machine rides the server lifecycle:
            # each running server holds one ref on the process-global
            # sampler; the thread stops when the last server stops
            from . import timeseries

            self._ts_ref = timeseries.ensure_sampler() is not None
        return self

    def stop(self) -> None:
        """Clean shutdown: stop accepting, close the socket, join the
        serve thread, flush + stop the spool writer. Idempotent."""
        if self._thread is not None:
            self._httpd.shutdown()
            self._thread.join(timeout=10.0)
            self._thread = None
        self._httpd.server_close()
        if self._spool is not None:
            self._spool.stop()
            self._spool = None
        if getattr(self, "_ts_ref", False):
            from . import timeseries

            self._ts_ref = False
            timeseries.release_sampler()

    def __enter__(self) -> "MetricsServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def start_server(port: int = 0, host: str = "127.0.0.1") -> MetricsServer:
    """Convenience: construct + start in one call (the CLI wiring)."""
    return MetricsServer(port=port, host=host).start()
