"""The flight recorder: dump recent traces + telemetry on failure.

The Hadoop JobTracker's one genuinely great artifact was the failure
page: when a job died, the counters and task history at the moment of
death were frozen in place for the post-mortem. This module is that
page, reborn for the serving era: on a soak invariant breach, a circuit
breaker opening, or a structured build error, `flight_dump()` writes one
JSONL artifact holding

  1. a header line (reason, wall time, pid, caller-supplied context),
  2. one line per recent trace — the last-N request/build span trees
     from the trace ring (obs/trace.py), offending request included,
  3. a full TelemetryRegistry snapshot (counters + histograms).

Dumps are rate-limited per reason (TPU_IR_FLIGHT_INTERVAL seconds,
default 30) so a flapping breaker under chaos cannot fill a disk;
invariant breaches pass force=True — a correctness breach is never
dropped. Artifacts land in TPU_IR_FLIGHT_DIR (default: a `tpu_ir_flight`
directory under the system temp dir) unless the caller names a
directory. Read them with `jq`, or just less — one JSON object per line.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time

from ..utils import envvars
from .registry import get_registry
from .trace import recent_traces

# Version of the artifact's header/record shape; bump on any change a
# downstream parser (jq pipelines, the /flight endpoint) could trip over.
FLIGHT_SCHEMA = 1

_lock = threading.Lock()
_last_dump: dict[str, float] = {}
_seq = 0


def _next_seq() -> int:
    """Process-monotonic artifact sequence number: stamped into the
    header AND the filename, so concurrent dumps (operator trace-dump
    racing a breach dump) order unambiguously even within one wall-clock
    second."""
    global _seq
    with _lock:
        _seq += 1
        return _seq


def _min_interval_s() -> float:
    return envvars.get_float("TPU_IR_FLIGHT_INTERVAL")


def flight_dir() -> str:
    return (envvars.get_str("TPU_IR_FLIGHT_DIR")
            or os.path.join(tempfile.gettempdir(), "tpu_ir_flight"))


def reset_rate_limit() -> None:
    """Forget dump timestamps (test isolation)."""
    with _lock:
        _last_dump.clear()


def artifact_lines(reason: str, extra: dict | None = None,
                   seq: int | None = None) -> list[str]:
    """THE flight-recorder artifact shape, one JSON string per line:
    header (schema, seq, reason, wall time, pid, extra context), then
    one trace record per ring entry, then a full registry snapshot.
    Shared by flight_dump and `tpu-ir trace-dump` so an operator dump
    and a breach dump are byte-shape-identical and cannot drift.

    `extra` may be a callable producing the dict — flight_dump defers
    expensive context assembly (the slow-query trap's explain
    dispatches) behind its rate-limit gate this way."""
    header = {
        "record": "header",
        "schema": FLIGHT_SCHEMA,
        "seq": _next_seq() if seq is None else seq,
        "reason": reason,
        "time": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "pid": os.getpid(),
    }
    try:
        # memory + compile-cache state AT the moment of failure: a
        # post-mortem can tell an OOM-adjacent breach or a recompile
        # storm from the header alone (lazy import — profiling imports
        # this module for storm dumps)
        from .profiling import compile_cache_snapshot, memory_snapshot

        header["memory"] = memory_snapshot()
        header["compile_cache"] = compile_cache_snapshot()
    except Exception:  # noqa: BLE001 — the header must always write
        pass
    try:
        # the last-K slow-query entries (hash, level, stage split): a
        # breach dump answers "what was slow just before this" without
        # a separate /querylog scrape (lazy import — querylog imports
        # this module for trap dumps)
        from .querylog import slow_header_entries

        header["slow_queries"] = slow_header_entries()
    except Exception:  # noqa: BLE001 — the header must always write
        pass
    try:
        # the distributed-trace id of the OPEN request on this thread
        # (ISSUE 18 bugfix): read from the live context + current_root,
        # NOT the ring — by dump time the ring may have evicted (or
        # sampled out) the root span of the very request whose failure
        # triggered this dump, and the header's join key must survive
        # that (lazy import — disttrace imports flight_dump)
        from .disttrace import current_trace_id
        from .trace import current_root

        tid = current_trace_id()
        if tid is not None:
            header["trace_id"] = tid
            root = current_root()
            if root is not None:
                header["open_root"] = {"name": root.name,
                                       "attrs": dict(root.attrs)}
    except Exception:  # noqa: BLE001 — the header must always write
        pass
    try:
        # the lead-up (ISSUE 19): the last-N retained windows of every
        # curated series, so a post-mortem carries the half-hour BEFORE
        # the breach, not just the instant of it (lazy import —
        # timeseries calls flight_dump for anomaly records)
        from .timeseries import header_window

        hw = header_window()
        if hw is not None:
            header["timeseries"] = hw
    except Exception:  # noqa: BLE001 — the header must always write
        pass
    if callable(extra):
        try:
            extra = extra()
        except Exception:  # noqa: BLE001 — deferred context must not
            extra = None   # kill the artifact that reports the failure
    if extra:
        header["extra"] = extra
    lines = [json.dumps(header, default=repr)]
    for span in recent_traces():
        lines.append(json.dumps({"record": "trace",
                                 "trace": span.to_dict()}, default=repr))
    lines.append(json.dumps({"record": "telemetry",
                             "telemetry": get_registry().snapshot()},
                            default=repr))
    return lines


def flight_dump(reason: str, extra: dict | None = None,
                out_dir: str | None = None, force: bool = False,
                ) -> str | None:
    """Write one flight-recorder artifact; returns its path, or None
    when rate-limited (same `reason` dumped within the interval and not
    forced). Never raises: the recorder runs inside failure paths, and a
    full disk must not convert a degraded request into a crashed one."""
    global _seq
    now = time.monotonic()
    with _lock:
        if not force and now - _last_dump.get(
                reason, -1e18) < _min_interval_s():
            return None
        _last_dump[reason] = now
        _seq += 1
        seq = _seq
    try:
        d = out_dir or flight_dir()
        os.makedirs(d, exist_ok=True)
        safe = "".join(c if c.isalnum() or c in "._-" else "_"
                       for c in reason)
        path = os.path.join(
            d, f"flight-{time.strftime('%Y%m%dT%H%M%S')}-"
               f"{os.getpid()}-{seq:03d}-{safe}.jsonl")
        with open(path, "w") as f:
            f.write("\n".join(artifact_lines(reason, extra, seq=seq))
                    + "\n")
        return path
    except Exception:  # noqa: BLE001 — see docstring
        return None


def recent_headers(out_dir: str | None = None, limit: int = 32) -> list:
    """Header lines of the newest flight artifacts in `out_dir` (default:
    flight_dir()), newest first, each with its file path attached — the
    `/flight` endpoint's index of recent incidents. Unreadable or
    foreign files are skipped, never raised: this runs inside a scrape."""
    d = out_dir or flight_dir()
    try:
        names = [n for n in os.listdir(d)
                 if n.startswith("flight-") and n.endswith(".jsonl")]
    except OSError:
        return []
    def _mtime(p: str) -> float:
        try:
            return os.path.getmtime(p)
        except OSError:  # deleted between listdir and stat — skippable,
            return 0.0   # not raisable: this runs inside a scrape
    paths = sorted((os.path.join(d, n) for n in names),
                   key=_mtime, reverse=True)
    out = []
    for path in paths[:limit]:
        try:
            with open(path) as f:
                header = json.loads(f.readline())
        except (OSError, ValueError):
            continue
        if isinstance(header, dict) and header.get("record") == "header":
            header["path"] = path
            out.append(header)
    return out
