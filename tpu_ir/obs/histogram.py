"""Fixed log-bucket latency histograms with percentile estimation.

The telemetry layer's distribution primitive: a histogram is a fixed
array of counters over power-of-two latency buckets, so recording an
observation is one bisect over a 39-entry tuple plus one locked
increment — the same lock discipline as the existing counters, no
allocation, no per-observation float math beyond the running sum. The
shared bucket layout (module constants, never per-instance) is what
makes histograms mergeable and delta-able: two snapshots subtract
bucket-wise, a merge is a bucket-wise add, and a percentile estimate is
exact to within one bucket width by construction.

Bucket i covers (BOUNDS[i-1], BOUNDS[i]] seconds; bucket 0 additionally
absorbs everything <= 1 microsecond, and the last bucket is the overflow
for anything past ~275 s (a latency that long is an incident, not a
distribution point). Log base 2 keeps boundary membership exact for
representable floats — `bisect` over precomputed bounds, no log() whose
rounding could misfile a boundary value.
"""

from __future__ import annotations

import bisect
import threading

BASE_S = 1e-6           # upper bound of bucket 0: 1 microsecond
NUM_BUCKETS = 40        # covers (0, ~275 s] + one overflow bucket
# upper bounds of buckets 0..NUM_BUCKETS-2; the last bucket is unbounded
BOUNDS = tuple(BASE_S * 2.0 ** i for i in range(NUM_BUCKETS - 1))


def bucket_index(seconds: float) -> int:
    """The bucket an observation lands in. Boundary values belong to the
    bucket they bound (bucket i is (BOUNDS[i-1], BOUNDS[i]]): bisect_left
    returns the first bound >= the value, which IS that bucket — exact,
    no floating log."""
    if seconds <= BASE_S:
        return 0
    i = bisect.bisect_left(BOUNDS, seconds)
    return min(i, NUM_BUCKETS - 1)


def percentile_from_counts(counts, q: float) -> float | None:
    """Estimate the q-th percentile (0..100) from a bucket-count array:
    find the bucket holding the target rank, interpolate linearly inside
    it. The true sample percentile lies in the same bucket, so the
    estimate is within one bucket width of exact (pinned by tests)."""
    total = sum(counts)
    if total == 0:
        return None
    rank = max(1, -(-int(q * total) // 100))  # ceil(q/100 * total), >= 1
    cum = 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        if cum + c >= rank:
            lo = BOUNDS[i - 1] if i > 0 else 0.0
            hi = BOUNDS[i] if i < len(BOUNDS) else BOUNDS[-1] * 2.0
            return lo + (hi - lo) * (rank - cum) / c
        cum += c
    return BOUNDS[-1] * 2.0  # unreachable unless counts mutate mid-walk


def summary_from_counts(counts, total_s: float) -> dict:
    """The JSON-facing digest of one bucket-count array: count, total
    time, and p50/p95/p99 estimates in milliseconds (None when empty)."""
    n = sum(counts)
    out = {"count": n, "sum_ms": round(total_s * 1e3, 3)}
    for q in (50, 95, 99):
        p = percentile_from_counts(counts, q)
        out[f"p{q}_ms"] = None if p is None else round(p * 1e3, 4)
    return out


class LatencyHistogram:
    """Thread-safe fixed log-bucket histogram (seconds)."""

    __slots__ = ("_lock", "_counts", "_sum_s")

    def __init__(self):
        self._lock = threading.Lock()
        self._counts = [0] * NUM_BUCKETS
        self._sum_s = 0.0

    def observe(self, seconds: float) -> None:
        i = bucket_index(seconds)
        with self._lock:
            self._counts[i] += 1
            self._sum_s += seconds

    @property
    def count(self) -> int:
        with self._lock:
            return sum(self._counts)

    def state(self) -> tuple[list[int], float]:
        """(bucket counts copy, total seconds) — the delta/merge unit."""
        with self._lock:
            return list(self._counts), self._sum_s

    def merge(self, other: "LatencyHistogram") -> None:
        """Add `other`'s observations into this histogram. Equivalent to
        having observed the concatenated sample (shared bucket layout);
        other's state is snapshotted first so no lock ordering issue."""
        counts, sum_s = other.state()
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self._sum_s += sum_s

    def drain(self) -> tuple[list[int], float]:
        """Atomically read AND zero (counts, total seconds): the
        per-interval scrape primitive — an observation can land in
        exactly one interval, never between two."""
        with self._lock:
            counts, sum_s = self._counts, self._sum_s
            self._counts = [0] * NUM_BUCKETS
            self._sum_s = 0.0
            return counts, sum_s

    def percentile(self, q: float) -> float | None:
        counts, _ = self.state()
        return percentile_from_counts(counts, q)

    def summary(self) -> dict:
        counts, sum_s = self.state()
        return summary_from_counts(counts, sum_s)

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * NUM_BUCKETS
            self._sum_s = 0.0
