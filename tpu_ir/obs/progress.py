"""JobTracker-style job/progress tracking.

The reference repo's entire perf record is 8 saved Hadoop JobTracker
HTML pages: per-job tables of map/reduce task counters with a
percent-complete column, frozen at job end. PR 3 rebuilt the data side
(spans, histograms, one registry); this module rebuilds the JOB side —
a live, process-wide model of what each long-running operation (an
index build, a serving soak) is currently doing:

- `start_job(kind, name, phases=...)` registers a Job with an ordered
  phase list (the JobTracker's map/shuffle/reduce rows). Each phase
  holds `done`/`total` task counts plus free-form counters (docs
  parsed, spills written, shuffle bytes, shards reduced, requests
  served).
- `report_progress(phase, advance=..., total=..., **counters)` is the
  hook threaded through the builders and the soak: it targets the
  newest unfinished job and is a cheap no-op when none is running, so
  library code calls it unconditionally.
- Percent-complete is derived per phase (done/total) and overall
  (completed phases count 1.0; the mean over declared phases), and is
  CONTRACTUALLY non-decreasing over a job's lifetime — `/jobs` pollers
  plot it without smoothing. The ETA comes from the current phase's
  observed throughput (done/elapsed), like the JobTracker's.
- Finished jobs stay in a bounded last-K history (`TPU_IR_JOB_HISTORY`,
  default 16) — the in-memory equivalent of the 8 saved pages.

Serving surface: `tpu_ir/obs/server.py` renders `jobs()` as `/jobs` and
`/jobs/<id>` (JSON + a minimal HTML page echoing the JobTracker
layout). Everything here is thread-safe; soak worker threads report
completions into the same job their driver registered.
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import threading
import time

from ..utils import envvars

# Version of Job.to_dict()'s shape (the /jobs payload); bump on any
# change a poller could trip over.
JOB_SCHEMA = 1

_lock = threading.Lock()
_jobs: collections.deque = collections.deque(
    maxlen=envvars.get_int("TPU_IR_JOB_HISTORY"))
_ids = itertools.count(1)


class Job:
    """One tracked operation: an ordered set of phases, each with
    done/total task counts and free-form counters. All mutation goes
    through report()/finish() under the job's lock; `seq` bumps on
    every mutation so a poller can cheaply detect change."""

    def __init__(self, kind: str, name: str, phases=(), config=None):
        self.job_id = next(_ids)
        self.kind = kind
        self.name = name
        self.config = dict(config or {})
        self.state = "running"
        self.error: str | None = None
        self.started = time.time()
        self.finished_at: float | None = None
        self.seq = 0
        self._lock = threading.Lock()
        self._phases: dict[str, dict] = {
            p: {"done": 0, "total": None, "counters": {},
                "started": None, "unit": None} for p in phases}
        self._current: str | None = None
        self._max_percent = 0.0

    # -- mutation ----------------------------------------------------------

    def report(self, phase: str | None, advance: int = 0,
               total: int | None = None, unit: str | None = None,
               **counters) -> None:
        """Record progress against `phase` (created on first mention and
        made the current phase; None targets whatever phase is current —
        the shape shared helpers like the SPMD shuffle use, since they
        run under different phases in different builds): `advance` bumps
        its done count, `total` (re)declares its task count, `unit`
        labels what the tasks ARE (a radix build's pass 2 counts
        "buckets" where the legacy build counts "batches" — the /jobs
        page renders the label so the done/total needle is readable),
        and keyword counters add into its free-form counter table. Safe
        from any thread."""
        with self._lock:
            if phase is None:
                phase = self._current or "main"
            st = self._phases.get(phase)
            if st is None:
                st = self._phases[phase] = {
                    "done": 0, "total": None, "counters": {},
                    "started": None, "unit": None}
            if st["started"] is None:
                st["started"] = time.time()
            if self._current != phase:
                # entering a later phase closes the earlier ones for the
                # percent computation (phases run in declaration order)
                self._current = phase
            if total is not None:
                st["total"] = int(total)
            if unit is not None:
                st["unit"] = unit
            if advance:
                st["done"] += int(advance)
            for k, v in counters.items():
                st["counters"][k] = st["counters"].get(k, 0) + v
            self.seq += 1

    def finish(self, error: str | None = None) -> None:
        with self._lock:
            if self.state != "running":
                return
            self.state = "failed" if error else "succeeded"
            self.error = error
            self.finished_at = time.time()
            self.seq += 1

    # -- derived views -----------------------------------------------------

    def _percent_locked(self) -> float:
        if self.state == "succeeded":
            return 100.0
        names = list(self._phases)
        if not names:
            return 0.0
        cur = (names.index(self._current)
               if self._current in names else -1)
        frac = 0.0
        for i, n in enumerate(names):
            st = self._phases[n]
            if i < cur:
                frac += 1.0  # an entered later phase closes earlier ones
            elif i == cur:
                if st["total"]:
                    frac += min(st["done"] / st["total"], 1.0)
        pct = 100.0 * frac / len(names)
        # the monotonicity contract: a late total revision (e.g. a resume
        # discovering more batches) must never walk the needle backwards
        self._max_percent = max(self._max_percent, pct)
        return round(self._max_percent, 2)

    def _eta_locked(self) -> float | None:
        """Seconds to current-phase completion at observed throughput."""
        if self.state != "running" or self._current is None:
            return None
        st = self._phases.get(self._current)
        if not st or not st["total"] or not st["done"]:
            return None
        elapsed = time.time() - (st["started"] or self.started)
        if elapsed <= 0:
            return None
        rate = st["done"] / elapsed
        remaining = max(st["total"] - st["done"], 0)
        return round(remaining / rate, 1) if rate > 0 else None

    def to_dict(self) -> dict:
        with self._lock:
            phases = []
            for name, st in self._phases.items():
                row = {"phase": name, "done": st["done"],
                       "total": st["total"],
                       "counters": dict(st["counters"])}
                if st.get("unit"):
                    row["unit"] = st["unit"]
                if st["total"]:
                    row["percent"] = round(
                        100.0 * min(st["done"] / st["total"], 1.0), 2)
                phases.append(row)
            out = {
                "schema": JOB_SCHEMA,
                "job_id": self.job_id,
                "kind": self.kind,
                "name": self.name,
                "state": self.state,
                "seq": self.seq,
                "started": time.strftime(
                    "%Y-%m-%dT%H:%M:%S", time.localtime(self.started)),
                "elapsed_s": round(
                    (self.finished_at or time.time()) - self.started, 3),
                "percent": self._percent_locked(),
                "current_phase": self._current,
                "phases": phases,
                "config": dict(self.config),
            }
            eta = self._eta_locked()
            if eta is not None:
                out["eta_s"] = eta
            if self.error:
                out["error"] = self.error
            return out


def start_job(kind: str, name: str, *, phases=(), config=None) -> Job:
    """Register a new running Job (it becomes the report_progress
    target). The caller owns finishing it — wrap the operation in
    try/finally and call job.finish(error=...) on the failure path."""
    job = Job(kind, name, phases=phases, config=config)
    with _lock:
        _jobs.append(job)
    return job


def current_job() -> Job | None:
    """The newest still-running job (None when idle)."""
    with _lock:
        for job in reversed(_jobs):
            if job.state == "running":
                return job
    return None


def report_progress(phase: str | None, advance: int = 0,
                    total: int | None = None, unit: str | None = None,
                    **counters) -> None:
    """THE hook the builders/soak call: forward to the current job, or
    do nothing when no job is registered (a bare library call — e.g. a
    test driving build_index directly — must pay one lock + deque scan,
    nothing more)."""
    job = current_job()
    if job is not None:
        job.report(phase, advance=advance, total=total, unit=unit,
                   **counters)


@contextlib.contextmanager
def tracked(kind: str, name: str, *, phases=(), config=None):
    """Register a job for the duration of a `with` block: finished
    `succeeded` on clean exit, `failed` (with the exception repr) when
    one escapes. The builders' one-line wrapping."""
    job = start_job(kind, name, phases=phases, config=config)
    try:
        yield job
    except BaseException as e:
        job.finish(error=repr(e))
        raise
    else:
        job.finish()


def jobs() -> list:
    """The bounded job history, oldest first (running jobs included)."""
    with _lock:
        return list(_jobs)


def get_job(job_id: int) -> Job | None:
    with _lock:
        for job in _jobs:
            if job.job_id == job_id:
                return job
    return None


def clear_jobs() -> None:
    """Forget all jobs (test isolation — obs.reset_all calls this)."""
    with _lock:
        _jobs.clear()
