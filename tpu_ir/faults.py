"""Deterministic fault injection, supervised retry, and structured errors.

The reference engine inherited its fault story from Hadoop for free: failed
tasks re-run, stragglers run speculatively, finished outputs are skipped on
restart (SURVEY §5, BuildIntDocVectorsForwardIndex.java:186-194). tpu-ir
rebuilt the resume-by-artifact half (index/streaming.py) but had no way to
PROVE any failure actually recovers. This module is that proof machinery plus
the recovery primitives themselves:

- **FaultPlan**: a process-wide, seeded, deterministic plan mapping named
  injection *sites* (threaded through the build and serve paths at file /
  batch granularity, never inner loops) to firing rules. Configured
  programmatically, or from the `TPU_IR_FAULTS` env var / `--faults` CLI
  flag. With no plan installed every site is one `is None` check — zero
  overhead on the production path.
- **RetryPolicy / run_with_retry**: supervised retry with attempt caps and
  jittered exponential backoff (deterministically seeded), raising a
  structured `BuildError` on exhaustion — the policy object that replaces
  ad-hoc retry loops (e.g. the all_to_all capacity doubling in
  parallel/sharded_build.py).
- **Structured errors**: `BuildError` (retry exhaustion), `IntegrityError`
  (checksum mismatch / corrupt artifact), `DeviceLoss` and
  `ScoreDeadlineExceeded` (the degraded-serving triggers), `InjectedCrash`
  (simulated mid-pass process death; a BaseException so recovery code that
  catches Exception cannot accidentally swallow a "death").
- **run_with_deadline**: bounded-latency execution of a device dispatch; on
  expiry the call is abandoned (daemon thread) and the caller falls back to
  a degraded path instead of hanging — "The Tail at Scale"'s
  latency-bounding applied to the score dispatch.

Spec grammar (env var / CLI): comma-separated `site[@match]:rule` entries,
plus an optional `seed=N`. Rules:

    once@K      fire exactly on the K-th hit of the site (1-based)
    first@N     fire on the first N hits
    p=F         fire each hit with probability F (seeded, deterministic)
    always      fire on every hit
    sleep=S     (modifier) sleep S seconds instead of raising, for hang sites

Example: `TPU_IR_FAULTS="spill_write@pairs-:first@2,crash.pass2:once@3"`.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass, field

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# structured errors
# ---------------------------------------------------------------------------


class BuildError(RuntimeError):
    """A build stage failed permanently after supervised retry: carries the
    stage name, the attempt count, and the final cause — the single
    structured surface a driver/operator sees instead of a raw traceback."""

    def __init__(self, stage: str, attempts: int, cause: BaseException | str):
        self.stage = stage
        self.attempts = attempts
        self.cause = cause
        super().__init__(
            f"build stage {stage!r} failed after {attempts} attempt(s): "
            f"{cause}")


class IntegrityError(AssertionError):
    """An artifact failed its integrity check (checksum mismatch, truncated
    or unreadable file). Carries the offending path so the operator knows
    exactly what to quarantine/rebuild. Subclasses AssertionError so it
    honors verify_index's long-standing "raises AssertionError with a
    specific message on violation" contract — a checksum mismatch is the
    byte-level sibling of the structural asserts."""

    def __init__(self, path: str, detail: str):
        self.path = path
        self.detail = detail
        super().__init__(f"artifact integrity failure: {path}: {detail}")
        # one canonical ledger site: every integrity failure — whichever
        # loader or verifier detects it — is observable in `tpu-ir stats`
        # (the counter was documented since PR 1 but never incremented;
        # the lint contract pass now pins emitted == declared)
        from .utils.report import recovery_counters

        recovery_counters().incr("integrity_failures")


class DeviceLoss(RuntimeError):
    """Simulated (or detected) loss of the scoring device mid-dispatch."""


class ScoreDeadlineExceeded(RuntimeError):
    """A score dispatch exceeded its per-batch deadline."""

    def __init__(self, deadline_s: float):
        self.deadline_s = deadline_s
        super().__init__(f"score dispatch exceeded {deadline_s}s deadline")


class InjectedCrash(BaseException):
    """Simulated mid-pass process death. Deliberately NOT an Exception:
    retry supervisors and defensive `except Exception` blocks must treat it
    like a real SIGKILL — unswallowable — so resume correctness is tested
    against the same propagation a dying process has."""


def is_device_loss(exc: BaseException) -> bool:
    """Whether an exception from a device dispatch means the DEVICE is gone
    (degrade) rather than the program is wrong (raise). Conservative: only
    the injected marker and XLA errors whose message names a lost/halted
    device qualify — a compile/shape error must never silently degrade."""
    if isinstance(exc, DeviceLoss):
        return True
    msg = str(exc).lower()
    return any(tag in msg for tag in
               ("device_lost", "device lost", "data_loss",
                "device halted", "device unavailable"))


# ---------------------------------------------------------------------------
# fault plan
# ---------------------------------------------------------------------------


@dataclass
class FaultSpec:
    """Firing rule for one site (see module docstring for the grammar)."""

    mode: str                 # "once" | "first" | "prob" | "always"
    arg: float = 0.0          # K for once, N for first, F for prob
    match: str | None = None  # substring the site key must contain
    sleep_s: float = 0.0      # hang duration for sleep-modified sites
    hits: int = field(default=0, compare=False)
    fired: int = field(default=0, compare=False)

    def should_fire(self, key: str | None, rng: random.Random) -> bool:
        if self.match is not None and (key is None or self.match not in key):
            return False
        self.hits += 1
        if self.mode == "once":
            fire = self.hits == int(self.arg)
        elif self.mode == "first":
            fire = self.hits <= int(self.arg)
        elif self.mode == "prob":
            fire = rng.random() < self.arg
        else:  # always
            fire = True
        if fire:
            self.fired += 1
        return fire


class FaultPlan:
    """Process-wide deterministic fault plan: site name -> [FaultSpec]."""

    def __init__(self, specs: dict[str, list[FaultSpec]] | None = None,
                 seed: int = 0):
        self.specs: dict[str, list[FaultSpec]] = dict(specs or {})
        self.seed = seed
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def add(self, site: str, rule: str = "always", *, match: str | None = None,
            sleep_s: float = 0.0) -> "FaultPlan":
        """Programmatic plan building: plan.add('spill_write', 'first@2')."""
        spec = _parse_rule(rule)
        spec.match = match
        spec.sleep_s = sleep_s
        self.specs.setdefault(site, []).append(spec)
        return self

    def should_fire(self, site: str, key: str | None = None) -> FaultSpec | None:
        """The spec that fired for this hit of `site`, or None. Thread-safe
        and deterministic: hit counters and the seeded RNG advance only for
        sites that have specs."""
        specs = self.specs.get(site)
        if not specs:
            return None
        with self._lock:
            for spec in specs:
                if spec.should_fire(key, self._rng):
                    logger.warning("fault injected at site %r (key=%r)",
                                   site, key)
                    # mirror the fire into the unified registry: plan
                    # counters die with the plan object, the registry's
                    # fault.<site> ledger survives for stats/metrics
                    from .obs.registry import get_registry

                    get_registry().incr(f"fault.{site}")
                    return spec
        return None

    def counters(self) -> dict[str, int]:
        return {site: sum(s.fired for s in specs)
                for site, specs in self.specs.items() if specs}


def _parse_rule(rule: str) -> FaultSpec:
    rule = rule.strip()
    if rule == "always":
        return FaultSpec("always")
    if rule.startswith("once@"):
        return FaultSpec("once", float(rule[5:]))
    if rule.startswith("first@"):
        return FaultSpec("first", float(rule[6:]))
    if rule.startswith("p="):
        return FaultSpec("prob", float(rule[2:]))
    raise ValueError(f"unknown fault rule {rule!r} "
                     "(expected once@K / first@N / p=F / always)")


def parse_plan(text: str) -> FaultPlan:
    """Parse the TPU_IR_FAULTS / --faults spec string into a FaultPlan."""
    seed = 0
    entries = []
    for part in filter(None, (p.strip() for p in text.split(","))):
        if part.startswith("seed="):
            seed = int(part[5:])
        else:
            entries.append(part)
    plan = FaultPlan(seed=seed)
    for part in entries:
        head, _, tail = part.partition(":")
        rule = tail or "always"
        sleep_s = 0.0
        if rule.startswith("sleep="):       # bare modifier: rule = always
            sleep_s, rule = float(rule[6:]), "always"
        elif ":sleep=" in rule:             # rule:sleep=S
            rule, _, s = rule.partition(":sleep=")
            sleep_s = float(s)
        site, _, match = head.partition("@")
        plan.add(site, rule, match=match or None, sleep_s=sleep_s)
    return plan


# the installed plan; None = everything disabled (the production state).
# Injection sites read this module attribute with one `is None` test.
_PLAN: FaultPlan | None = None
_ENV_CHECKED = False


def install(plan: FaultPlan | None) -> None:
    """Install (or with None, clear) the process-wide fault plan."""
    global _PLAN, _ENV_CHECKED
    _PLAN = plan
    _ENV_CHECKED = True  # explicit install overrides the env var


def clear() -> None:
    global _PLAN, _ENV_CHECKED
    _PLAN = None
    _ENV_CHECKED = False


def active() -> FaultPlan | None:
    """The installed plan, lazily picking up TPU_IR_FAULTS on first use."""
    global _PLAN, _ENV_CHECKED
    if not _ENV_CHECKED:
        _ENV_CHECKED = True
        from .utils import envvars

        spec = envvars.get_str("TPU_IR_FAULTS")
        if spec:
            _PLAN = parse_plan(spec)
    return _PLAN


def should_fire(site: str, key: str | None = None) -> FaultSpec | None:
    """Hot-path probe: one attribute read + None test when no plan is
    installed and the env var is absent."""
    plan = _PLAN if _ENV_CHECKED else active()
    if plan is None:
        return None
    return plan.should_fire(site, key)


def maybe_crash(site: str, key: str | None = None) -> None:
    """Injection point for simulated mid-pass process death."""
    if should_fire(site, key) is not None:
        raise InjectedCrash(f"injected crash at {site}")


def maybe_hang(site: str, key: str | None = None) -> None:
    """Injection point for slow/hung dispatches: sleeps the spec's
    `sleep_s` (default 30s — long enough to trip any sane deadline)."""
    spec = should_fire(site, key)
    if spec is not None:
        time.sleep(spec.sleep_s or 30.0)


# ---------------------------------------------------------------------------
# supervised retry
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class RetryPolicy:
    """Attempt-capped jittered exponential backoff. `seed` makes the jitter
    sequence deterministic (the whole fault story is replayable)."""

    max_attempts: int = 4
    base_delay_s: float = 0.05
    multiplier: float = 2.0
    jitter: float = 0.25      # +/- fraction of the delay
    seed: int = 0

    def delay_s(self, attempt: int, rng: random.Random) -> float:
        d = self.base_delay_s * (self.multiplier ** (attempt - 1))
        return max(0.0, d * (1.0 + self.jitter * (2 * rng.random() - 1)))


# transient host-filesystem writes (spill / part files)
SPILL_RETRY = RetryPolicy(max_attempts=4, base_delay_s=0.02)
# all_to_all capacity renegotiation: supplies the backoff/jitter between
# re-dispatches; the attempt BOUND there is the capacity ceiling C (see
# sharded_build_postings), not max_attempts — a count below feasibility
# would fail legitimately skewed distributions
OVERFLOW_RETRY = RetryPolicy(max_attempts=8, base_delay_s=0.0)


def run_with_retry(fn, *, policy: RetryPolicy = SPILL_RETRY, stage: str,
                   retry_on: tuple = (OSError,), report=None,
                   sleep=time.sleep):
    """Run `fn()` under the policy; returns its value. Retries only
    `retry_on` exceptions (InjectedCrash is a BaseException and always
    propagates — a death is not a transient). On exhaustion raises
    BuildError carrying the stage and final cause. Each retry increments
    the process recovery counters (and `report`'s, when given) so every
    recovery is observable."""
    rng = random.Random(policy.seed)
    last: BaseException | None = None
    for attempt in range(1, policy.max_attempts + 1):
        try:
            return fn()
        except retry_on as e:
            last = e
            if attempt == policy.max_attempts:
                break
            from .utils.report import recovery_counters

            recovery_counters().incr("retries")
            if report is not None:
                report.incr("Fault.RETRIES")
            logger.warning("stage %r attempt %d/%d failed (%s); retrying",
                           stage, attempt, policy.max_attempts, e)
            sleep(policy.delay_s(attempt, rng))
    from .obs.recorder import flight_dump
    from .utils.report import recovery_counters

    recovery_counters().incr("retry_exhausted")
    # a structured error is a flight-recorder trigger: freeze the recent
    # traces + telemetry for the post-mortem (rate-limited, never raises)
    flight_dump("build_error", extra={
        "stage": stage, "attempts": policy.max_attempts,
        "cause": repr(last)})
    raise BuildError(stage, policy.max_attempts, last) from last


# ---------------------------------------------------------------------------
# deadlines
# ---------------------------------------------------------------------------


# abandoned dispatch threads still blocked on a hung device; bounded so a
# dead device plus a steady query stream cannot grow threads without limit
_ABANDONED_CAP = 4
_abandoned: list[threading.Thread] = []
_abandoned_lock = threading.Lock()


def run_with_deadline(fn, deadline_s: float | None):
    """Run `fn()` with a wall-clock deadline. None = run inline (zero
    overhead). On expiry the worker thread is abandoned (daemon — a truly
    hung device call cannot block process exit) and ScoreDeadlineExceeded
    raises so the caller can fall back instead of hanging.

    Abandoned threads are tracked and capped at _ABANDONED_CAP live ones:
    once the cap is hit the device is presumed hung and further deadlined
    calls fail fast (immediate ScoreDeadlineExceeded, no new thread, no
    deadline wait) until an abandoned dispatch finally returns. An
    abandoned call that completes later has its result discarded; any
    lazy state it populated (e.g. a Scorer's cached matrices) is
    assignment-atomic, so the cost is wasted work, not corruption."""
    if deadline_s is None:
        return fn()
    with _abandoned_lock:
        _abandoned[:] = [t for t in _abandoned if t.is_alive()]
        if len(_abandoned) >= _ABANDONED_CAP:
            raise ScoreDeadlineExceeded(deadline_s)
    box: dict = {}
    # re-parent the worker onto the caller's open span so the kernel
    # spans inside fn() land in the request's trace tree instead of
    # surfacing as orphan roots on the dispatch thread
    from .obs import attach as obs_attach
    from .obs import current_span as obs_current_span

    parent_span = obs_current_span()

    def run():
        try:
            with obs_attach(parent_span):
                box["r"] = fn()
        except BaseException as e:  # delivered to the caller below
            box["e"] = e

    t = threading.Thread(target=run, daemon=True,
                         name="tpu-ir-score-dispatch")
    t.start()
    t.join(deadline_s)
    if t.is_alive():
        with _abandoned_lock:
            _abandoned.append(t)
        raise ScoreDeadlineExceeded(deadline_s)
    if "e" in box:
        raise box["e"]
    return box["r"]


def drain_abandoned(timeout_s: float = 5.0) -> int:
    """Best-effort bounded join of abandoned dispatch threads; returns
    how many are STILL alive. Soak drivers and test harnesses call this
    before process exit: the threads are daemon (they cannot block
    exit), but one still inside a device dispatch during interpreter
    teardown can abort the XLA runtime — draining first makes shutdown
    quiet."""
    deadline = time.monotonic() + timeout_s
    with _abandoned_lock:
        threads = list(_abandoned)
    for t in threads:
        t.join(max(deadline - time.monotonic(), 0.0))
    with _abandoned_lock:
        _abandoned[:] = [t for t in _abandoned if t.is_alive()]
        return len(_abandoned)
