"""Query-highlighted text snippets from the document store.

With `tpu-ir index --store` the raw document text survives next to the
index (index/docstore.py); `tpu-ir search --snippets` renders, for each
hit, a window of the ORIGINAL text centered on the densest cluster of
query-term matches, with the matching words wrapped in ``**``.

Matching reuses the indexing analyzer: a display word matches when its
analyzed form (tag tokenizer + stopwords + Porter2) hits a query token —
so "Fishing," highlights for the query "fish" exactly when the index
matched it, and never on raw substring accidents. The reference has no
equivalent (its engine returns docnos only; Indexable content is
discarded at index time)."""

from __future__ import annotations

import re

_TAG_RE = re.compile(r"<[^>\n]{0,256}>")
_WS_RE = re.compile(r"\s+")
# metadata elements whose CONTENT is not document text: the docid (the
# caller already printed it) and trecweb's HTTP header block
_META_RE = re.compile(r"<(DOCNO|DOCHDR)>.*?</\1>", re.S | re.I)

SNIPPET_WORDS = 16   # window width in display words
MARK = "**"
# rendering work is bounded: at most this much raw record text is ever
# considered for a snippet (a multi-MB document must not make every
# query that hits it crawl through the analyzer word by word)
SNIPPET_SCAN_BYTES = 1 << 20
# the first this-many display words are scanned EXACTLY (densest-cluster
# selection, identical to an unbounded scan); past it the scan may stop
# at a window that already covers every distinct query token
SNIPPET_EXACT_WORDS = 4096


def display_text(content: str) -> str:
    """Raw stored record -> displayable text: metadata elements removed
    wholesale, remaining tags dropped, whitespace collapsed."""
    return _WS_RE.sub(
        " ", _TAG_RE.sub(" ", _META_RE.sub(" ", content))).strip()


def make_snippet(content: str, query_tokens: set[str], analyzer,
                 width: int = SNIPPET_WORDS,
                 scan_bytes: int = SNIPPET_SCAN_BYTES,
                 exact_words: int = SNIPPET_EXACT_WORDS) -> str:
    """One highlighted window. `query_tokens` are ANALYZED query tokens
    (token-level, not k-grams — phrase/k-gram queries highlight their
    component words).

    Work is bounded (VERDICT r4 weak #3) without changing results for
    normal documents: documents shorter than `exact_words` display words
    get the full densest-cluster selection (identical to an unbounded
    scan). Past `exact_words`, the scan stops as soon as some window has
    covered every distinct query token; the shown window is then the
    full-coverage one, unless a strictly denser cluster was already seen
    (the unbounded scan's choice for the scanned region). `scan_bytes`
    caps the raw text considered at all when the query never fully
    co-occurs."""
    truncated = len(content) > scan_bytes
    if truncated:
        # cut at whitespace: a mid-word (or mid-tag) slice would leak a
        # partial token like '</TEX' past the tag-stripping regexes
        cut = content[:scan_bytes]
        ws = max(cut.rfind(" "), cut.rfind("\n"), cut.rfind("\t"))
        content = cut[:ws] if ws > 0 else cut
    words = display_text(content).split(" ")
    if not words:
        return ""
    # memoize per call: documents repeat words heavily, and the analyzer
    # (tokenize + stopwords + Porter2) is the scan's whole cost
    memo: dict[str, frozenset] = {}

    def matched_tokens(w: str) -> frozenset:
        hit = memo.get(w)
        if hit is None:
            hit = memo[w] = frozenset(
                t for t in analyzer.analyze(w) if t in query_tokens)
        return hit

    # one forward scan with a sliding window over the hit positions:
    # densest cluster so far, plus the best FULL-coverage window (every
    # distinct query token inside) for the bounded early exit
    hits: list[int] = []
    hit_toks: list[frozenset] = []
    best_lo, best_n = 0, 0
    full: tuple[int, int] | None = None
    j = 0
    window_count: dict[str, int] = {}
    for i, w in enumerate(words):
        toks = matched_tokens(w)
        if toks:
            hits.append(i)
            hit_toks.append(toks)
            for t in toks:
                window_count[t] = window_count.get(t, 0) + 1
            while hits[j] < i - width + 1:
                for t in hit_toks[j]:
                    window_count[t] -= 1
                    if not window_count[t]:
                        del window_count[t]
                j += 1
            if len(hits) - j > best_n:
                best_n, best_lo = len(hits) - j, hits[j]
            if (len(window_count) == len(query_tokens)
                    and (full is None or len(hits) - j > full[1])):
                full = (hits[j], len(hits) - j)
        if i >= exact_words and full is not None:
            # bounded region: stop scanning — a window already shows the
            # whole query. Show it unless the exact region found a
            # strictly DENSER cluster (what an unbounded scan of that
            # region would have picked)
            if full[1] >= best_n:
                best_lo, best_n = full
            break

    if not hits:
        head = " ".join(words[:width])
        return head + (" ..." if len(words) > width or truncated else "")
    # center the cluster; a cluster spanning the full window gets shift 0
    # (a forced shift of 1 would cut its last matched word off)
    lo = max(0, best_lo - max((width - best_n) // 2, 0))
    hi = min(len(words), lo + width)
    hit_set = set(hits)
    # words past an early-exit position were never analyzed; they can
    # appear unhighlighted at the window's tail — the bounded-work
    # contract trades that for not scanning multi-MB docs to the end
    out = [(MARK + w + MARK) if i in hit_set else w
           for i, w in enumerate(words[lo:hi], lo)]
    return (("... " if lo > 0 else "") + " ".join(out)
            + (" ..." if hi < len(words) or truncated else ""))
