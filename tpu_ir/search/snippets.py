"""Query-highlighted text snippets from the document store.

With `tpu-ir index --store` the raw document text survives next to the
index (index/docstore.py); `tpu-ir search --snippets` renders, for each
hit, a window of the ORIGINAL text centered on the densest cluster of
query-term matches, with the matching words wrapped in ``**``.

Matching reuses the indexing analyzer: a display word matches when its
analyzed form (tag tokenizer + stopwords + Porter2) hits a query token —
so "Fishing," highlights for the query "fish" exactly when the index
matched it, and never on raw substring accidents. The reference has no
equivalent (its engine returns docnos only; Indexable content is
discarded at index time)."""

from __future__ import annotations

import re

_TAG_RE = re.compile(r"<[^>\n]{0,256}>")
_WS_RE = re.compile(r"\s+")
# metadata elements whose CONTENT is not document text: the docid (the
# caller already printed it) and trecweb's HTTP header block
_META_RE = re.compile(r"<(DOCNO|DOCHDR)>.*?</\1>", re.S | re.I)

SNIPPET_WORDS = 16   # window width in display words
MARK = "**"


def display_text(content: str) -> str:
    """Raw stored record -> displayable text: metadata elements removed
    wholesale, remaining tags dropped, whitespace collapsed."""
    return _WS_RE.sub(
        " ", _TAG_RE.sub(" ", _META_RE.sub(" ", content))).strip()


def make_snippet(content: str, query_tokens: set[str], analyzer,
                 width: int = SNIPPET_WORDS) -> str:
    """One highlighted window. `query_tokens` are ANALYZED query tokens
    (token-level, not k-grams — phrase/k-gram queries highlight their
    component words)."""
    words = display_text(content).split(" ")
    if not words:
        return ""
    # memoize per call: documents repeat words heavily, and the analyzer
    # (tokenize + stopwords + Porter2) is the scan's whole cost
    memo: dict[str, bool] = {}

    def matches(w: str) -> bool:
        hit = memo.get(w)
        if hit is None:
            hit = memo[w] = any(t in query_tokens
                                for t in analyzer.analyze(w))
        return hit

    hits = [i for i, w in enumerate(words) if matches(w)]
    if not hits:
        head = " ".join(words[:width])
        return head + (" ..." if len(words) > width else "")
    # densest cluster: the window position covering the most hits
    # (hits is small — one pass with two pointers)
    best_lo, best_n = hits[0], 1
    j = 0
    for i, h in enumerate(hits):
        while hits[j] < h - width + 1:
            j += 1
        if i - j + 1 > best_n:
            best_n, best_lo = i - j + 1, hits[j]
    lo = max(0, best_lo - max((width - best_n) // 2, 1))
    hi = min(len(words), lo + width)
    hit_set = set(hits)
    out = [(MARK + w + MARK) if i in hit_set else w
           for i, w in enumerate(words[lo:hi], lo)]
    return (("... " if lo > 0 else "") + " ".join(out)
            + (" ..." if hi < len(words) else ""))
