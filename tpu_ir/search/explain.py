"""Exact per-term score decomposition — the query-level "why" lens.

Lucene answers "why did this doc rank here" with `Explanation` trees; the
reference engine (a batch Hadoop pipeline) had nothing. This module is
the TPU-native version of that lens, built so the numbers are not a
re-derivation that can drift from the production kernels but the
kernels' OWN floats:

- Every score readout comes from a debug *scores-at-docs* variant of the
  production kernel (ops/scoring.py `*_scores_at_*`,
  parallel/sharded_tiered.py `sharded_tiered_scores_at`) that traces the
  IDENTICAL accumulation expression and merely gathers the requested
  docnos instead of running top-k — so the gathered score for a returned
  hit is bit-identical to the score the production dispatch ranked it by.

- Per-term contributions are *marginal deltas in accumulation order*:
  the query's L slots become an (L+1)-row prefix batch (row j holds the
  first j term ids, the rest PAD), scored in ONE dispatch; slot l's
  contribution is float64(S_l) - float64(S_{l-1}). PAD slots contribute
  exact 0.0 to every accumulation stage, so S_l is the kernel's own
  partial sum — and the float64 telescoped total collapses exactly to
  S_L, the production score. That identity is the hard contract
  tests/test_explain.py pins bit-exactly across dense/tiered/sharded
  layouts and the hot_only / skip_hot / prune kernel variants (the
  score-bound bookkeeping argument WAND-style pruning correctness
  proofs lean on, here applied to the whole scoring stack).

Metadata (tf, df, idf, length norm, tier placement, prune/skip decision,
rerank delta) rides alongside from the host-side arrays. The per-term tf
lookup needs the CSR postings columns; on the serving-cache fast path
those assemble lazily on first use (same documented one-time cost as the
host fallback scorer — see Scorer._topk_host) and `tf` is None when a
Scorer was built from serving arrays only.
"""

from __future__ import annotations

import numpy as np

from ..obs import trace as obs_trace

# BM25 constants — THE shared pair (search/phrase.py re-exports the same)
K1 = 0.9
B = 0.4


def _idf_host(scorer, scoring: str) -> np.ndarray:
    """Host copy of the exact idf vector the kernels use (computed by the
    same ops functions on device, fetched once and cached per model)."""
    from ..ops.scoring import bm25_idf_weights, idf_weights

    import jax.numpy as jnp

    key = (scoring, scorer.compat_int_idf)
    cache = getattr(scorer, "_explain_idf_cache", None)
    if cache is None:
        cache = scorer._explain_idf_cache = {}
    if key not in cache:
        n = scorer.meta.num_docs
        if scoring == "bm25":
            w = bm25_idf_weights(scorer.df, jnp.float32(n))
        else:
            w = idf_weights(scorer.df, jnp.int32(n),
                            scorer.compat_int_idf)
        cache[key] = np.asarray(w)
    return cache[key]


def _csr_for_tf(scorer):
    """(indptr, pair_doc, pair_tf) for host tf lookups, or None when the
    Scorer carries no postings columns (serving arrays only). The O(V)
    indptr cumsum is cached on the scorer — an explain touches it once,
    not once per (term, doc)."""
    try:
        pd, ptf = scorer._pairs_doc_tf
    except RuntimeError:
        return None
    indptr = getattr(scorer, "_explain_indptr_cache", None)
    if indptr is None:
        indptr = np.concatenate(
            [[0], np.cumsum(scorer._df_host(), dtype=np.int64)])
        scorer._explain_indptr_cache = indptr
    return indptr, pd, ptf


def _tf_in_doc(csr, tid: int, docno: int) -> int | None:
    """Raw tf of term `tid` in `docno` from the host CSR columns."""
    if csr is None:
        return None
    indptr, pd, ptf = csr
    run = pd[int(indptr[tid]) : int(indptr[tid + 1])]
    hits = np.nonzero(run == docno)[0]
    if not len(hits):
        return 0
    return int(ptf[int(indptr[tid]) + int(hits[0])])


def _placement(scorer, tid: int, docno: int) -> dict:
    """Where the term's postings live in the serving layout (the tier
    lens: hot strip vs which cold tier; plus the owning shard on the
    distributed layout)."""
    if scorer.layout == "dense":
        return {"placement": "dense"}
    if scorer.layout == "sharded":
        lay = scorer._sharded
        shard = max((int(docno) - 1) // lay.dblk, 0)
        hr = _host_cache(scorer, "_explain_sh_hot_rank", lay.hot_rank)
        tof = _host_cache(scorer, "_explain_sh_tier_of", lay.tier_of)
        if hr[shard, tid] >= 0:
            place = "hot"
        elif tof[shard, tid] >= 0:
            place = f"tier:{int(tof[shard, tid])}"
        else:
            place = "absent"
        return {"placement": place, "shard": shard}
    hr = scorer._hot_rank_host()
    if hr[tid] >= 0:
        return {"placement": "hot"}
    tof = _host_cache(scorer, "_explain_tier_of", scorer.tier_of)
    if tof[tid] >= 0:
        return {"placement": f"tier:{int(tof[tid])}"}
    return {"placement": "absent"}


def _host_cache(scorer, attr: str, device_array) -> np.ndarray:
    a = getattr(scorer, attr, None)
    if a is None:
        a = np.asarray(device_array)
        setattr(scorer, attr, a)
    return a


def _scores_at(scorer, q: np.ndarray, docs: np.ndarray, *, scoring: str,
               skip_hot: bool = False, hot_only: bool = False
               ) -> np.ndarray:
    """[B, C] f32 production-kernel scores at docnos `docs`, via the
    debug gather variants (shared accumulation with the top-k kernels)."""
    import jax.numpy as jnp

    from ..ops.scoring import (
        bm25_scores_at_dense,
        bm25_scores_at_tiered,
        tfidf_scores_at_dense,
        tfidf_scores_at_tiered,
    )

    qd = jnp.asarray(q, jnp.int32)
    cand = jnp.asarray(docs, jnp.int32)
    n = jnp.int32(scorer.meta.num_docs)
    if scorer.layout == "sharded":
        from ..parallel.sharded_tiered import sharded_tiered_scores_at

        out = sharded_tiered_scores_at(
            qd, scorer._sharded, scorer._df_mesh, scorer.meta.num_docs,
            cand, mesh=scorer._mesh, scoring=scoring,
            compat_int_idf=scorer.compat_int_idf, hot_only=hot_only)
    elif scorer.layout == "dense":
        if scoring == "bm25":
            out = bm25_scores_at_dense(qd, scorer._ensure_tf_matrix(),
                                       scorer.df, scorer.doc_len, n, cand)
        else:
            out = tfidf_scores_at_dense(
                qd, scorer.doc_matrix, scorer.df, n, cand,
                compat_int_idf=scorer.compat_int_idf)
    elif scoring == "bm25":
        out = bm25_scores_at_tiered(
            qd, scorer.hot_rank, scorer.hot_tfs, scorer.tier_of,
            scorer.row_of, scorer.tier_docs, scorer.tier_tfs, scorer.df,
            scorer.doc_len, n, cand, num_docs=scorer.meta.num_docs,
            skip_hot=skip_hot, hot_only=hot_only)
    else:
        out = tfidf_scores_at_tiered(
            qd, scorer.hot_rank, scorer.hot_tfs, scorer.tier_of,
            scorer.row_of, scorer.tier_docs, scorer.tier_tfs, scorer.df,
            n, cand, num_docs=scorer.meta.num_docs,
            compat_int_idf=scorer.compat_int_idf, skip_hot=skip_hot,
            hot_only=hot_only)
    return np.asarray(out)


def _cosine_scores_at(scorer, q: np.ndarray, cand: np.ndarray
                      ) -> np.ndarray:
    """[B, C] per-candidate cosine (rerank stage-2) scores in candidate
    order, via the debug variants of the production rerank kernels."""
    import jax.numpy as jnp

    from ..ops.scoring import cosine_scores_at_dense, cosine_scores_at_tiered

    qd = jnp.asarray(q, jnp.int32)
    cd = jnp.asarray(cand, jnp.int32)
    n = jnp.int32(scorer.meta.num_docs)
    if scorer.layout == "sharded":
        from ..parallel.sharded_tiered import sharded_tiered_cosine_at

        out = sharded_tiered_cosine_at(
            qd, scorer._sharded, scorer._df_mesh, scorer.meta.num_docs,
            scorer._ensure_sharded_norm(), cd, mesh=scorer._mesh)
    elif scorer.layout == "dense":
        out = cosine_scores_at_dense(qd, scorer.doc_matrix, scorer.df,
                                     scorer._doc_norms(), cd, n)
    else:
        out = cosine_scores_at_tiered(
            qd, scorer.hot_rank, scorer.hot_tfs, scorer.tier_of,
            scorer.row_of, scorer.tier_docs, scorer.tier_tfs, scorer.df,
            scorer._doc_norms(), n, cd, num_docs=scorer.meta.num_docs)
    return np.asarray(out)


def _prefix_batch(ids: list[int], width: int) -> np.ndarray:
    """The (L+1)-row prefix query batch (row j = first j ids, rest PAD),
    row count padded to a power of two so explain dispatches reuse a
    small compile ladder (the analyze_queries width-bucketing argument,
    applied to the batch axis)."""
    rows = len(ids) + 1
    cap = 1 << max(rows - 1, 0).bit_length()
    qp = np.full((cap, width), -1, np.int32)
    for j in range(1, rows):
        qp[j, :j] = ids[:j]
    return qp


def _telescope(prefix_scores: np.ndarray) -> list[float]:
    """Marginal per-slot contributions: float64 deltas of consecutive
    prefix scores. Their sum collapses exactly (term-by-term
    cancellation in float64) to prefix_scores[-1] - prefix_scores[0]."""
    s = prefix_scores.astype(np.float64)
    return [float(s[j] - s[j - 1]) for j in range(1, len(s))]


def explain_hits(scorer, text: str, docnos, *, scoring: str = "tfidf",
                 rerank: int | None = None, hot_only: bool = False,
                 ) -> list[dict]:
    """Explain dicts for `docnos` (iterable of ints) under one query —
    one combined prefix-batch dispatch for all docs (plus one candidate
    generation + one cosine dispatch when `rerank` is set).

    Each dict decomposes the score the production pipeline would report
    for that (query, doc): per-slot marginal contributions under the
    final ranking model (BM25/TF-IDF for plain top-k, the cosine stage
    for rerank), with tf/df/idf/length-norm/tier metadata per term and
    the query-level prune/skip dispatch decision."""
    docnos = [int(d) for d in docnos]
    with obs_trace("explain", docs=len(docnos), scoring=scoring,
                   rerank=rerank or 0):
        return _explain_hits(scorer, text, docnos, scoring=scoring,
                             rerank=rerank, hot_only=hot_only)


def _explain_hits(scorer, text, docnos, *, scoring, rerank, hot_only):
    q = scorer.analyze_queries([text])
    ids = [int(t) for t in q[0] if t >= 0]
    width = q.shape[1]
    n_docs = scorer.meta.num_docs

    # the dispatch decision the production topk() scheduler would make
    # for this query (search/scorer.py::_skip_plan): hot-free queries on
    # the tiered layout run the static cold-only kernel
    skip_hot = False
    dispatch = {"layout": scorer.layout, "hot_only": bool(hot_only),
                "skip_hot": False, "prune_scheduling": False}
    if scorer.layout == "sparse" and scorer.prune and not hot_only:
        has_hot = bool(scorer._has_hot(q)[0]) if ids else False
        skip_hot = not has_hot
        dispatch.update({"prune_scheduling": True, "has_hot_terms": has_hot,
                         "skip_hot": skip_hot})

    qp = _prefix_batch(ids, width)
    docs_ok = [d for d in docnos if 1 <= d <= n_docs]
    cand = np.tile(np.asarray(docs_ok, np.int32)[None, :] if docs_ok
                   else np.zeros((1, 1), np.int32), (len(qp), 1))

    stage1 = None
    if docs_ok:
        if rerank:
            # production two-stage pipeline: stage 1 regenerates the BM25
            # candidate set exactly as _rerank_primary does, stage 2 reads
            # the cosine scores out of a candidate matrix of the SAME
            # shape — identical traced reduction, identical floats
            import jax.numpy as jnp

            _, cand_d = scorer._topk_device(jnp.asarray(q, jnp.int32),
                                            rerank, "bm25")
            cand_row = np.asarray(cand_d)[:1]            # [1, C]
            cand_full = np.tile(cand_row, (len(qp), 1))
            prefix = _cosine_scores_at(scorer, qp, cand_full)  # [B*, C]
            stage1 = _scores_at(scorer, q, np.asarray([docs_ok], np.int32),
                                scoring="bm25")[0]
            # map each explained doc to its column in the candidate set
            col_of = {int(d): j for j, d in
                      reversed(list(enumerate(cand_row[0])))}
        else:
            prefix = _scores_at(scorer, qp, cand, scoring=scoring,
                                skip_hot=skip_hot, hot_only=hot_only)
    idf = _idf_host(scorer, "tfidf" if rerank else scoring)
    csr = _csr_for_tf(scorer)
    df_host = scorer._df_host()
    doc_len = np.asarray(scorer.doc_len)
    avg_dl = float(doc_len.astype(np.float64).sum()) / max(n_docs, 1)
    norms = None
    if rerank:
        norms = scorer._doc_norms_host()

    out = []
    for d in docnos:
        entry = {
            "query": text,
            "docno": d,
            "docid": None,
            "scoring": "cosine_rerank" if rerank else scoring,
            "layout": scorer.layout,
            "dispatch": dispatch,
            "score": 0.0,
            "contribution_sum": 0.0,
            "terms": [],
        }
        try:
            entry["docid"] = scorer.mapping.get_docid(d)
        except Exception:  # noqa: BLE001 — ids are a nicety, not the lens
            pass
        if not 1 <= d <= n_docs:
            entry["error"] = f"docno {d} out of range 1..{n_docs}"
            out.append(entry)
            continue
        entry["doc_len"] = int(doc_len[d])
        if scoring == "bm25" and not rerank:
            entry["avg_doc_len"] = round(avg_dl, 4)
            entry["dl_norm"] = float(
                1.0 - B + B * float(doc_len[d]) / max(avg_dl, 1e-9))
            entry["k1"], entry["b"] = K1, B
        if rerank:
            j = col_of.get(d)
            if j is None:
                # the doc never made the stage-1 candidate set (explain
                # of an arbitrary doc, not a returned hit): read its
                # cosine score through a 1-candidate gather — right
                # value, but not the production candidate-matrix shape
                solo = np.tile(np.asarray([[d]], np.int32), (len(qp), 1))
                col = _cosine_scores_at(scorer, qp, solo)[:, 0]
                entry["rerank"] = {"in_candidates": False,
                                   "candidates": rerank}
            else:
                col = prefix[:, j]
                entry["rerank"] = {
                    "in_candidates": True,
                    "candidates": rerank,
                    "stage1_score": float(stage1[docs_ok.index(d)])
                    if d in docs_ok else None,
                }
            entry["doc_norm"] = float(norms[d])
        else:
            col = prefix[:, docs_ok.index(d)]
        col = col[: len(ids) + 1]
        contribs = _telescope(col)
        entry["score"] = float(col[len(ids)])
        entry["contribution_sum"] = float(np.sum(
            np.asarray(contribs, np.float64)))
        if rerank and entry["rerank"].get("stage1_score") is not None:
            entry["rerank"]["delta"] = float(
                np.float64(entry["score"])
                - np.float64(entry["rerank"]["stage1_score"]))
        for slot, tid in enumerate(ids):
            t = {
                "slot": slot,
                "term": scorer.vocab.term(tid),
                "term_id": tid,
                "df": int(df_host[tid]),
                "idf": float(idf[tid]),
                "tf": _tf_in_doc(csr, tid, d),
                "contribution": contribs[slot],
            }
            t.update(_placement(scorer, tid, d))
            entry["terms"].append(t)
        out.append(entry)
    return out
