"""Wildcard term lookup over the char-k-gram index.

The reference builds the char-k-gram -> term index "for wildcard/fuzzy term
lookup" (SURVEY.md §0; CharKGramTermIndexer.java) but ships no query-side
consumer for it — lookup was done by inspecting the index manually. We close
that gap: a `te*d`-style pattern is decomposed into its $-padded k-grams,
the per-gram sorted term-id lists are intersected, and a final literal scan
filters false positives (the classic k-gram postfilter).
"""

from __future__ import annotations

import fnmatch
import itertools
import os
from functools import reduce

import numpy as np

from ..collection import Vocab
from ..index import format as fmt
from ..index.builder import TOKENS_VOCAB
from ..ops import gram_to_code

# fuzzy cost ceiling shared by every surface (query tokens, CLI expand):
# the k-gram count filter weakens fast past 2 edits, degenerating toward
# a vocabulary-wide Levenshtein scan
MAX_FUZZY_EDITS = 2


class WildcardLookup:
    def __init__(self, vocab: Vocab, k: int, gram_codes: np.ndarray,
                 indptr: np.ndarray, term_ids: np.ndarray):
        self.vocab = vocab
        self.k = k
        self._codes = gram_codes
        self._indptr = indptr
        self._term_ids = term_ids
        self._lazy_dir: str | None = None

    @classmethod
    def load(cls, index_dir: str, k: int,
             vocab: Vocab | None = None) -> "WildcardLookup":
        """`vocab` lets a caller that already holds the token vocabulary
        (e.g. a k=1 Scorer, whose index vocab IS the token vocab) share it
        instead of re-reading it from disk. The gram arrays themselves load
        lazily on first expansion — a Scorer holds one lookup per chargram
        k but a typical pattern only ever consults the largest k."""
        if vocab is None:
            tok_vocab_path = os.path.join(index_dir, TOKENS_VOCAB)
            vocab = Vocab.load(
                tok_vocab_path if os.path.exists(tok_vocab_path)
                else os.path.join(index_dir, fmt.VOCAB))
        out = cls(vocab, k, None, None, None)
        out._lazy_dir = index_dir
        return out

    def _ensure_loaded(self) -> None:
        if self._codes is None:
            z = fmt.load_chargram(self._lazy_dir, self.k)
            self._codes = z["gram_codes"]
            self._indptr = z["indptr"]
            self._term_ids = z["term_ids"]

    def _terms_for_gram(self, gram: bytes) -> np.ndarray:
        code = gram_to_code(gram, self.k)
        i = np.searchsorted(self._codes, code)
        if i >= len(self._codes) or self._codes[i] != code:
            return np.zeros(0, np.int32)
        return self._term_ids[self._indptr[i] : self._indptr[i + 1]]

    def pattern_grams(self, pattern: str) -> list[bytes]:
        """k-grams implied by a wildcard pattern: pad with $ at fixed ends,
        take grams of every maximal wildcard-free run. Grams are UTF-8
        *byte* windows, matching how the index packs terms (a multi-byte
        character spans several byte grams, same as in `pack_term_bytes`)."""
        padded = "$" + pattern + "$"
        runs = [r.encode("utf-8")
                for r in padded.replace("?", "*").split("*") if r]
        grams = []
        for run in runs:
            grams.extend(
                run[i : i + self.k] for i in range(len(run) - self.k + 1))
        return grams

    def fuzzy(self, term: str, max_edits: int = 1,
              limit: int | None = None) -> list[tuple[str, int]]:
        """Vocabulary terms within `max_edits` Levenshtein edits of
        `term`, as (term, distance) sorted by (distance, term).

        The other half of the char-k-gram index's stated purpose
        (SURVEY.md §0: built "for wildcard/fuzzy term lookup"; the
        reference shipped neither consumer). Classic k-gram filtering:
        one edit disturbs at most k of the $-padded byte grams, so a
        match shares >= n_grams - max_edits*k grams with the query —
        candidates come from one bincount over the per-gram term lists,
        then a banded edit-distance postfilter (characters, not bytes)
        confirms. When the bound collapses (short terms vs large k:
        len(grams) - max_edits*k < 1) the threshold floors at 1 shared
        gram — a RECALL loss for terms shorter than ~k+edits, since a
        1-edit neighbor can share zero k-grams ('cat'/'cut' at k=3);
        callers with several chargram ks should pick one that keeps the
        bound positive (Scorer._fuzzy_terms does). Multi-byte text also
        relaxes the threshold to 1 (one character edit can disturb up to
        4*k byte grams). `max_edits=0` is an exact vocabulary probe."""
        self._ensure_loaded()
        q = term
        if max_edits < 1:  # Lucene's ~0: exact match only
            return [(q, 0)] if q in self.vocab else []
        qb = ("$" + q + "$").encode("utf-8")
        grams = list(dict.fromkeys(          # distinct grams: the count
            qb[i : i + self.k]               # filter is per shared gram
            for i in range(len(qb) - self.k + 1)))
        if not grams:
            return []
        ascii_q = len(qb) == len(q) + 2
        thr = (max(len(grams) - max_edits * self.k, 1) if ascii_q else 1)
        counts = np.zeros(len(self.vocab.terms), np.int32)
        for g in grams:
            counts[self._terms_for_gram(g)] += 1
        out = []
        for tid in np.nonzero(counts >= thr)[0]:
            t = self.vocab.term(int(tid))
            d = _levenshtein_capped(q, t, max_edits)
            if d is not None:
                out.append((t, d))
        out.sort(key=lambda td: (td[1], td[0]))
        return out[:limit] if limit is not None else out

    def expand(self, pattern: str, limit: int | None = None) -> list[str]:
        """Vocabulary terms matching a glob pattern (e.g. 'te*', '*tion')."""
        grams = self.pattern_grams(pattern)
        self._ensure_loaded()
        if grams:
            lists = [self._terms_for_gram(g) for g in grams]
            if any(len(l) == 0 for l in lists):
                return []
            cand_ids = reduce(np.intersect1d, lists)
            cands = (self.vocab.term(int(t)) for t in cand_ids)
        else:
            cands = iter(self.vocab.terms)  # pattern like '*': scan all
        matches = (t for t in cands if fnmatch.fnmatchcase(t, pattern))
        # early exit: candidates arrive in sorted-term order either way, so
        # stopping at `limit` returns the same prefix a full scan would
        # (matters for single-gram patterns like 'a*' whose candidate set is
        # a vocabulary-scale slice)
        if limit is not None:
            return list(itertools.islice(matches, limit))
        return list(matches)


def _levenshtein_capped(a: str, b: str, cap: int) -> int | None:
    """Levenshtein distance if <= cap, else None. Banded DP: only the
    diagonal band of width 2*cap+1 is computed, with an early abort when
    a full row exceeds the cap — O(cap * max(len)) per candidate."""
    if a == b:
        return 0
    la, lb = len(a), len(b)
    if abs(la - lb) > cap:
        return None
    if la > lb:  # keep the inner loop over the shorter string's band
        a, b, la, lb = b, a, lb, la
    big = cap + 1
    prev = list(range(la + 1))
    for j in range(1, lb + 1):
        cur = [big] * (la + 1)
        cur[0] = j if j <= cap else big
        lo = max(1, j - cap)
        hi = min(la, j + cap)
        for i in range(lo, hi + 1):
            cost = 0 if a[i - 1] == b[j - 1] else 1
            cur[i] = min(prev[i] + 1,        # delete
                         cur[i - 1] + 1,     # insert
                         prev[i - 1] + cost)  # substitute
        if min(cur) > cap:
            return None
        prev = cur
    return prev[la] if prev[la] <= cap else None
