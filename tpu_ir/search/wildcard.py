"""Wildcard term lookup over the char-k-gram index.

The reference builds the char-k-gram -> term index "for wildcard/fuzzy term
lookup" (SURVEY.md §0; CharKGramTermIndexer.java) but ships no query-side
consumer for it — lookup was done by inspecting the index manually. We close
that gap: a `te*d`-style pattern is decomposed into its $-padded k-grams,
the per-gram sorted term-id lists are intersected, and a final literal scan
filters false positives (the classic k-gram postfilter).
"""

from __future__ import annotations

import fnmatch
import itertools
import os
from functools import reduce

import numpy as np

from ..collection import Vocab
from ..index import format as fmt
from ..index.builder import TOKENS_VOCAB
from ..ops import gram_to_code


class WildcardLookup:
    def __init__(self, vocab: Vocab, k: int, gram_codes: np.ndarray,
                 indptr: np.ndarray, term_ids: np.ndarray):
        self.vocab = vocab
        self.k = k
        self._codes = gram_codes
        self._indptr = indptr
        self._term_ids = term_ids
        self._lazy_dir: str | None = None

    @classmethod
    def load(cls, index_dir: str, k: int,
             vocab: Vocab | None = None) -> "WildcardLookup":
        """`vocab` lets a caller that already holds the token vocabulary
        (e.g. a k=1 Scorer, whose index vocab IS the token vocab) share it
        instead of re-reading it from disk. The gram arrays themselves load
        lazily on first expansion — a Scorer holds one lookup per chargram
        k but a typical pattern only ever consults the largest k."""
        if vocab is None:
            tok_vocab_path = os.path.join(index_dir, TOKENS_VOCAB)
            vocab = Vocab.load(
                tok_vocab_path if os.path.exists(tok_vocab_path)
                else os.path.join(index_dir, fmt.VOCAB))
        out = cls(vocab, k, None, None, None)
        out._lazy_dir = index_dir
        return out

    def _ensure_loaded(self) -> None:
        if self._codes is None:
            z = fmt.load_chargram(self._lazy_dir, self.k)
            self._codes = z["gram_codes"]
            self._indptr = z["indptr"]
            self._term_ids = z["term_ids"]

    def _terms_for_gram(self, gram: bytes) -> np.ndarray:
        code = gram_to_code(gram, self.k)
        i = np.searchsorted(self._codes, code)
        if i >= len(self._codes) or self._codes[i] != code:
            return np.zeros(0, np.int32)
        return self._term_ids[self._indptr[i] : self._indptr[i + 1]]

    def pattern_grams(self, pattern: str) -> list[bytes]:
        """k-grams implied by a wildcard pattern: pad with $ at fixed ends,
        take grams of every maximal wildcard-free run. Grams are UTF-8
        *byte* windows, matching how the index packs terms (a multi-byte
        character spans several byte grams, same as in `pack_term_bytes`)."""
        padded = "$" + pattern + "$"
        runs = [r.encode("utf-8")
                for r in padded.replace("?", "*").split("*") if r]
        grams = []
        for run in runs:
            grams.extend(
                run[i : i + self.k] for i in range(len(run) - self.k + 1))
        return grams

    def expand(self, pattern: str, limit: int | None = None) -> list[str]:
        """Vocabulary terms matching a glob pattern (e.g. 'te*', '*tion')."""
        grams = self.pattern_grams(pattern)
        self._ensure_loaded()
        if grams:
            lists = [self._terms_for_gram(g) for g in grams]
            if any(len(l) == 0 for l in lists):
                return []
            cand_ids = reduce(np.intersect1d, lists)
            cands = (self.vocab.term(int(t)) for t in cand_ids)
        else:
            cands = iter(self.vocab.terms)  # pattern like '*': scan all
        matches = (t for t in cands if fnmatch.fnmatchcase(t, pattern))
        # early exit: candidates arrive in sorted-term order either way, so
        # stopping at `limit` returns the same prefix a full scan would
        # (matters for single-gram patterns like 'a*' whose candidate set is
        # a vocabulary-scale slice)
        if limit is not None:
            return list(itertools.islice(matches, limit))
        return list(matches)
